"""Continuous-batching serving engine over the static KV-cache decode
path.

ONE compiled decode-step program (fixed ``[max_slots, 1]`` token block,
per-slot positions, active-slot mask — and, on the default PAGED
layout, the static page table) serves any mix of in-flight requests;
prefill compiles once per power-of-2 length bucket (full-prompt and
shared-prefix-extend flavors). Compare
``benchmarks/bench_llama_decode.py``'s synchronized path, where every
sequence in a batch starts and stops together and slots idle while the
longest request finishes — here freed slots are refilled from the
queue at every iteration (Orca-style iteration-level scheduling), so
ragged traffic keeps the batch dense, and the paged pool admits by
FREE PAGES rather than worst-case rows, so the same KV bytes carry
several times more concurrent requests (docs/SERVING.md).

Synchronous API by design (the repo's serving story is one compiled
program per step, driven by a host loop):

    engine = ServingEngine(model, max_slots=8, max_len=256, eos_id=2)
    r1 = engine.submit(prompt, max_new_tokens=32)
    while engine.has_work():
        finished = engine.step()
    print(r1.output_ids, engine.metrics.summary())

``speculative=True`` swaps the decode step for ONE widened k-token
VERIFY program fed by self-drafted n-gram proposals
(spec_decode.NgramProposer) — greedy outputs stay provably
token-identical to this path and to ``generate()``; see
docs/SERVING.md "Speculative decoding". Steps where no row has a
draft are GATED back onto the k=1 decode program (identical tokens at
1/k the compute; ``spec_gate=False`` pins the always-widened flavor).

``mesh=`` (a ProcessMesh with a single ``model`` axis) makes the
engine TENSOR-PARALLEL: KV pools shard on kv_heads, params by the
family's output-dim-only ``tp_param_spec`` rules, and every program
jits under the mesh with explicit shardings — still ONE decode
program per mesh shape, and still bitwise token-identical to the
single-chip engine. ``prefill_devices=k`` partitions the mesh into a
prefill group and a decode group with an explicit device_put KV
handoff between them (docs/SERVING.md "Multi-chip serving").

Resilience contract (docs/RESILIENCE.md): a step that fails with
donated cache pools marks the engine broken — ``recover()`` rebuilds
the slot-pool KV cache from host-side request state (re-prefilling
in-flight requests; greedy replay is verified token-identical) instead
of the old permanently-poisoned dead-end. Admission is bounded
(``max_queue`` → typed ``QueueFull``), requests carry optional
deadlines (cancelled at step boundaries with ``finish_reason ==
"deadline"``), and ``drain()`` shuts down gracefully. Fault points
``serving.step.decode`` / ``serving.step.prefill``
(resilience.faults) make every one of these paths testable on CPU.
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..observability import default_recorder, default_registry, span
from ..resilience.faults import InjectedFault, maybe_fail
from .errors import (DeadlineExceeded, EngineBroken, EngineClosed,
                     EngineIdle, QueueFull, RequestCancelled)
from .kv_tier import HostPageTier, PersistentPrefixStore
from .mesh import MeshContext
from .metrics import EngineMetrics
from .sampling import SamplingParams, sample_token, sampling_dist
from .scheduler import FIFOScheduler, Request, bucket_for
from .slot_cache import PagedKVCache, SlotKVCache
from .spec_decode import DraftModelProposer, NgramProposer
from .spec_tune import SpecTuner

__all__ = ["ServingEngine"]


class _ModelAdapter:
    """Uniform view over the causal LMs that expose the static-cache
    path (models/llama.py natively; models/gpt.py via its cache-aware
    forward): a backbone callable taking (ids, caches), a logits head,
    and the cache geometry."""

    def __init__(self, model):
        self.model = model
        # tensor-parallel shard rules for raw_state() param names
        # (serving/mesh.py builds NamedShardings from these); None =
        # every param replicated, which is always correct
        self.tp_param_spec = None
        if hasattr(model, "llama"):          # LlamaForCausalLM
            from ..models.llama import tp_param_spec
            self.tp_param_spec = tp_param_spec
            cfg = model.config
            backbone = model.llama
            self.call = lambda ids, caches: backbone(ids, None, caches)
            self.head = model._head
            self.num_layers = len(backbone.layers)
            self.head_dim = cfg.head_dim
            attn0 = backbone.layers[0].self_attn
            kp = attn0.k_proj       # Linear (weight) or Int8Linear (wq)
            kw = kp.weight if hasattr(kp, "weight") else kp.wq
            self.kv_heads = kw.shape[-1] // cfg.head_dim
            self.max_positions = cfg.max_position_embeddings
            self.dtype = backbone.embed_tokens.weight._data.dtype
        elif hasattr(model, "gpt"):          # GPTForCausalLM
            from ..models.gpt import tp_param_spec
            self.tp_param_spec = tp_param_spec
            cfg = model.cfg
            backbone = model.gpt
            self.call = lambda ids, caches: backbone(ids, caches=caches)
            self.head = model._head
            self.num_layers = len(backbone.blocks)
            self.head_dim = cfg.head_dim
            qw = backbone.blocks[0].qkv.weight
            self.kv_heads = qw.shape[-1] // (3 * cfg.head_dim)
            self.max_positions = cfg.max_seq_len
            self.dtype = backbone.wte.weight._data.dtype
        else:
            raise TypeError(
                f"{type(model).__name__} exposes no static-cache decode "
                "path the serving engine can drive (expected a .llama "
                "or .gpt backbone with a (k, v, pos) cache forward)")


class ServingEngine:
    """Slot-based continuous-batching engine (see module docstring)."""

    def __init__(self, model, max_slots: int = 8,
                 max_len: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 min_bucket: int = 16,
                 max_queue: Optional[int] = None,
                 time_fn: Callable[[], float] = time.perf_counter,
                 registry=None, flight_recorder=None,
                 auditor=None,
                 cancel_probe: Optional[Callable] = None,
                 kv_layout: str = "paged",
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 kv_dtype: Optional[str] = None,
                 prefix_sharing: Optional[bool] = None,
                 speculative: bool = False,
                 spec_k: int = 4,
                 spec_ngram: int = 2,
                 spec_gate: bool = True,
                 spec_proposer: str = "ngram",
                 draft_model=None,
                 spec_sampled: bool = False,
                 spec_tune: bool = False,
                 mesh=None,
                 prefill_devices: int = 0,
                 prefill_chunk: Optional[int] = None,
                 chunk_control=None,
                 admission_lookahead: int = 0,
                 kv_host_tier: bool = False,
                 host_tier_pages: Optional[int] = None,
                 prefix_store_dir: Optional[str] = None,
                 kv_transport=None):
        self.adapter = _ModelAdapter(model)
        model.eval()
        self.max_slots = int(max_slots)
        self.max_len = int(max_len or self.adapter.max_positions)
        if self.max_len > self.adapter.max_positions:
            raise ValueError(
                f"max_len {self.max_len} exceeds the model's position "
                f"range {self.adapter.max_positions}")
        self.eos_id = eos_id
        if max_queue is not None and max_queue < 1:
            raise ValueError(
                f"max_queue must be >= 1 or None, got {max_queue}")
        self.max_queue = max_queue
        self.min_bucket = min(int(min_bucket), self.max_len)
        # chunked prefill (docs/SERVING.md "Chunked prefill"): split
        # every admitted prompt into `prefill_chunk`-token chunks and
        # run at most ONE chunk per step alongside the decode program,
        # so a long prompt can never stall in-flight decodes for its
        # whole prefill. Power-of-2 and >= the bucket floor so every
        # non-final chunk IS its own bucket (zero padding) and the
        # chunk-program compile count stays O(log max_len).
        self.prefill_chunk = None
        if prefill_chunk is not None:
            c = int(prefill_chunk)
            if c < 1 or (c & (c - 1)):
                raise ValueError(
                    f"prefill_chunk must be a power of 2, got "
                    f"{prefill_chunk}")
            if bucket_for(c, self.min_bucket, self.max_len) != c:
                raise ValueError(
                    f"prefill_chunk {c} must be a prefill bucket "
                    f"(>= the min_bucket floor and <= max_len "
                    f"{self.max_len})")
            self.prefill_chunk = c
        # serving.control.ChunkBudgetController (optional, requires
        # prefill_chunk): scales the per-step prefill token budget as
        # a multiple of the FIXED compiled chunk — the chunk program
        # is one cached jit, so the budget changes how many times it
        # runs per step, never its shape. None keeps the legacy
        # at-most-one-chunk-per-step behaviour bit-identical.
        if chunk_control is not None and self.prefill_chunk is None:
            raise ValueError(
                "chunk_control requires prefill_chunk (the controller "
                "scales the chunked-prefill budget)")
        self.chunk_control = chunk_control
        if admission_lookahead < 0:
            raise ValueError(
                f"admission_lookahead must be >= 0, got "
                f"{admission_lookahead}")
        self.admission_lookahead = int(admission_lookahead)
        if kv_layout not in ("paged", "contiguous"):
            raise ValueError(
                f"kv_layout must be 'paged' or 'contiguous', got "
                f"{kv_layout!r}")
        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_dtype must be None (model dtype) or 'int8', got "
                f"{kv_dtype!r}")
        if kv_layout == "contiguous" and (
                page_size is not None or num_pages is not None
                or kv_dtype is not None or prefix_sharing is not None):
            raise ValueError(
                "page_size/num_pages/kv_dtype/prefix_sharing only "
                "apply to the paged kv_layout")
        self.paged = kv_layout == "paged"
        if self.paged:
            if page_size is None:
                # largest power-of-2 divisor of max_len, capped at 128
                # (the TPU-friendly default page)
                page_size = 128
                while self.max_len % page_size:
                    page_size //= 2
            self.page_size = int(page_size)
            self.num_pages = num_pages        # None = capacity parity
            self.kv_quant = kv_dtype == "int8"
            self.prefix_sharing = True if prefix_sharing is None \
                else bool(prefix_sharing)
        # KV tiering (docs/SERVING.md "KV tiering"): demote cold
        # refcount-0 prefix pages to pinned host RAM instead of
        # destroying them, promote back on radix hit; an optional
        # disk store under the RAM tier keeps shared prompts warm
        # across recover() and process restarts
        self.kv_host_tier = bool(kv_host_tier) \
            or prefix_store_dir is not None
        self.prefix_store_dir = prefix_store_dir
        if host_tier_pages is not None and not self.kv_host_tier:
            raise ValueError(
                "host_tier_pages requires kv_host_tier=True (or "
                "prefix_store_dir=)")
        if self.kv_host_tier:
            if not (self.paged and self.prefix_sharing):
                raise ValueError(
                    "kv_host_tier requires the paged kv_layout with "
                    "prefix_sharing enabled (the tier is keyed by "
                    "radix chunks)")
            if mesh is not None:
                raise ValueError(
                    "kv_host_tier is not supported on mesh engines "
                    "yet: demotion would have to gather sharded "
                    "pools per page (see ROADMAP)")
        # cross-host KV wire (serving/kv_wire.py): when set, every
        # disaggregated prefill->decode handoff round-trips its KV
        # blocks through the transport's digest-verified socket path
        # before the decode-side install — the seam a cross-host
        # prefill/decode split plugs into. Same staged/abort contract;
        # a KVWireError past the transport's retry budget aborts the
        # handoff exactly like a device-fabric failure.
        self.kv_transport = kv_transport
        if kv_transport is not None and prefill_devices <= 0:
            raise ValueError(
                "kv_transport requires a disaggregated mesh "
                "(prefill_devices > 0): only the prefill->decode "
                "handoff crosses the wire")
        # speculative decoding: drafts (n-gram lookup or a small draft
        # MODEL) verified k tokens per weight pass through ONE widened
        # verify program; greedy rows keep the bitwise identity law,
        # sampled rows opt into rejection-sampling acceptance via
        # spec_sampled=True, and spec_tune=True closes the loop from
        # the accepted-length EWMA back to per-step (k, proposer)
        # choices. See docs/SERVING.md "Speculative decoding".
        self.speculative = bool(speculative)
        if self.speculative:
            if spec_k < 2:
                raise ValueError(
                    f"spec_k must be >= 2 (k includes the k=1 base "
                    f"token), got {spec_k}")
            if spec_proposer not in ("ngram", "draft"):
                raise ValueError(
                    f"spec_proposer must be 'ngram' or 'draft', got "
                    f"{spec_proposer!r}")
            if spec_proposer == "draft" and draft_model is None:
                raise ValueError(
                    "spec_proposer='draft' requires draft_model=")
            self.spec_k = int(spec_k)
            # every configured proposer lives for the engine's
            # lifetime (the tuner switches between them per step) and
            # is admitted/evicted/recovered in lockstep via
            # _proposer_release/_proposer_retain
            self._proposers = {
                "ngram": NgramProposer(ngram=spec_ngram,
                                       max_draft=self.spec_k - 1)}
            if draft_model is not None:
                self._proposers["draft"] = DraftModelProposer(
                    draft_model, max_slots=self.max_slots,
                    max_len=self.max_len,
                    max_draft=self.spec_k - 1)
            self.spec_proposer = spec_proposer
            self.proposer = self._proposers[spec_proposer]
            self.spec_sampled = bool(spec_sampled)
            # skip the k-wide verify program on steps where NO row has
            # a draft (all wlen == 1): the k=1 decode program emits the
            # provably identical token at 1/k the verify compute.
            # Trace counts stay bounded: <= 1 decode + <= 1 verify.
            self.spec_gate = bool(spec_gate)
            # tuner starts optimistic on the CONFIGURED proposer and
            # probes the others round-robin once traffic stops paying
            self._tuner = SpecTuner(
                k_max=self.spec_k,
                proposers=tuple(
                    [self.spec_proposer]
                    + [k for k in self._proposers
                       if k != self.spec_proposer])) \
                if spec_tune else None
        elif spec_k != 4 or spec_ngram != 2 or spec_gate is not True \
                or spec_proposer != "ngram" or draft_model is not None \
                or spec_sampled or spec_tune:
            raise ValueError(
                "spec_k/spec_ngram/spec_gate/spec_proposer/"
                "draft_model/spec_sampled/spec_tune only apply with "
                "speculative=True")
        # tensor-parallel serving mesh (docs/SERVING.md "Multi-chip
        # serving"): KV pools + shardable params split over the
        # mesh's `model` axis; with prefill_devices > 0 the mesh is
        # PARTITIONED into a prefill group and a decode group and
        # finished prefill KV spans are handed off via device_put
        self.meshctx = None
        if mesh is not None:
            self.meshctx = MeshContext(mesh,
                                       kv_heads=self.adapter.kv_heads,
                                       prefill_devices=prefill_devices)
        elif prefill_devices:
            raise ValueError(
                "prefill_devices (disaggregated prefill/decode) "
                "requires mesh=")
        # rid -> slot for requests whose prefilled KV is computed on
        # the prefill group but not yet installed on the decode pool —
        # the cross-group no-leak law audits this is empty at quiesce
        self._staged_handoffs = {}
        # chunked-prefill state: PREFILLING slots in admission order
        # (the head advances one chunk per step) and, on disaggregated
        # engines, rid -> per-layer local KV buffers accumulating the
        # chunks on the PREFILL group until the final-span handoff.
        # Both are audited empty at quiesce (no-leak law).
        self._chunk_fifo: List[int] = []
        self._chunk_local = {}
        # name -> (source array, mesh-placed copy), per group:
        # re-placing every step would re-transfer params the model
        # still holds. Keyed by NAME with the source kept alive in the
        # entry (an id()-keyed cache would go stale when a checkpoint
        # load frees old arrays and a new one reuses the address)
        self._placed = {"decode": {}, "prefill": {}}
        # group -> (param-name key, shardings dict): the shardings are
        # static per (names, mesh), so don't rebuild NamedShardings on
        # every step
        self._shardings_cache = {}
        # host/disk KV tier OUTLIVES the cache object: recover()'s
        # _new_cache() rebinds a fresh radix tree onto the same tier
        # (rehydration), which is what keeps warm prefixes across
        # pool rebuilds
        self._kv_tier = None
        if self.kv_host_tier:
            ad = self.adapter
            store = None
            if prefix_store_dir is not None:
                store = PersistentPrefixStore(
                    prefix_store_dir, num_layers=ad.num_layers,
                    page_size=self.page_size, kv_heads=ad.kv_heads,
                    head_dim=ad.head_dim, dtype=ad.dtype,
                    quant=self.kv_quant)
            self._kv_tier = HostPageTier(
                ad.num_layers, self.page_size, ad.kv_heads,
                ad.head_dim, ad.dtype, quant=self.kv_quant,
                capacity_pages=host_tier_pages, store=store)
        # rid -> slot for requests whose host-tier pages are being
        # promoted onto fresh device pages but not yet committed —
        # audited empty at quiesce exactly like _staged_handoffs
        self._staged_promotions = {}
        self.cache = self._new_cache()
        self.scheduler = FIFOScheduler()
        self.registry = registry if registry is not None \
            else default_registry()
        # `is None`, not truthiness: an EMPTY FlightRecorder is falsy
        # (it has __len__), and `or` would silently swap it for the
        # global one
        self.recorder = flight_recorder if flight_recorder is not None \
            else default_recorder()
        self.metrics = EngineMetrics(self.max_slots, time_fn,
                                     registry=self.registry)
        self._params_pf = self._buffers_pf = None
        self._refresh_state()
        self._decode_jit = None
        self._verify_jit = None
        self._prefill_jit = None
        self._extend_jit = None
        self._copy_jit = None
        self._install_jit = None
        self._promote_jit = None
        self._chunk_jit = None
        self._chunk_local_jit = None
        self._chunk_fin_jit = None
        self._next_rid = 0
        self._step_idx = 0
        # set when a step fails after donating the cache pools (device
        # buffers invalidated); recover() clears it
        self._broken: Optional[str] = None
        self._closed = False
        # requests that reached a terminal state inside a FAILED step
        # (deadline sweep, decode finisher evicted before the raise) or
        # were discovered finished-in-slot by recover(): they must
        # still surface through the next successful step()/recover()/
        # drain() exactly once — never lost, never duplicated. The
        # list survives a recover() that itself faults mid-re-prefill.
        self._undelivered: List[Request] = []
        # optional conservation auditor (resilience.invariants duck
        # type: on_submitted(req) / on_delivered(req, via)) — called at
        # the EXTERNAL delivery boundaries only, so a ledger sees
        # exactly what callers see
        self.auditor = auditor
        # optional liveness callback(req) -> bool (True = the client
        # behind this request is gone). The front door installs one so
        # a disconnect observed on an HTTP thread propagates into
        # engine cancellation at the next safe point: the step-boundary
        # sweep, or mid-prefill AFTER pages are claimed (so the abort
        # path unwinds them). Requests also carry their own
        # `cancel_requested` flag, checked first.
        self.cancel_probe = cancel_probe
        # optional watchtower (observability.watchtower) installed by
        # Watchtower.attach_engine(); the step hot path bumps its
        # counter — one increment, nothing else (micro-asserted)
        self._watchtower = None
        self._in_drain = False
        # python-side-effect counters bumped at TRACE time: the compile-
        # count contract (1 decode + O(log max_len) prefill buckets) is
        # asserted against these in tests
        self.trace_counts = {"decode": 0, "verify": 0, "draft": 0,
                             "prefill": {},
                             "extend": {}, "copy": 0, "install": {},
                             "chunk": {}, "promote": 0}
        if self.speculative and "draft" in self._proposers:
            # the draft proposer's ONE compiled program bumps the
            # engine's own trace-count ledger, so the compile contract
            # (1 decode + 1 verify + 1 draft) is asserted in one place
            self._proposers["draft"].trace_counts = self.trace_counts
        reg = self.registry
        self._m_queue_depth = reg.gauge(
            "ptpu_serving_queue_depth", "requests waiting for a slot")
        self._m_active = reg.gauge(
            "ptpu_serving_active_slots", "slots decoding this step")
        self._m_step = reg.histogram(
            "ptpu_serving_step_seconds",
            "wall time of one engine iteration (engine clock)")
        self._m_prefill = reg.counter(
            "ptpu_serving_prefills_total", "prefill program runs",
            labels=("bucket",))
        self._m_evict = reg.counter(
            "ptpu_serving_evictions_total", "slots freed",
            labels=("reason",))
        self._m_reject = reg.counter(
            "ptpu_serving_rejected_total",
            "submissions refused at admission", labels=("reason",))
        self._m_deadline = reg.counter(
            "ptpu_serving_deadline_cancellations_total",
            "requests cancelled at their deadline (queued + in-flight)")
        self._m_disconnect = reg.counter(
            "ptpu_serving_disconnects_total",
            "requests cancelled because their client went away")
        self._m_recover = reg.counter(
            "ptpu_serving_recoveries_total",
            "successful recover() calls after a broken step")
        self._m_replay_mismatch = reg.counter(
            "ptpu_serving_recover_replay_mismatch_total",
            "recovery re-prefills whose greedy replay token diverged "
            "from the already-delivered token")
        if self.prefill_chunk is not None:
            self._m_chunk_steps = reg.counter(
                "ptpu_serving_chunk_steps_total",
                "chunked-prefill chunk program runs")
            self._m_chunk_depth = reg.gauge(
                "ptpu_serving_chunk_queue_depth",
                "PREFILLING requests mid-chunked-prefill")
        if self.paged:
            self._m_pages_free = reg.gauge(
                "ptpu_serving_pages_free", "KV pages on the free list")
            self._m_pages_active = reg.gauge(
                "ptpu_serving_pages_active",
                "KV pages referenced by at least one request")
            self._m_pages_cached = reg.gauge(
                "ptpu_serving_pages_cached",
                "refcount-0 prefix-index pages (reclaimable)")
            self._m_kv_bytes = reg.gauge(
                "ptpu_serving_kv_bytes",
                "total device bytes of the paged KV pool (+scales)")
            self._m_kv_bytes.set(self.cache.kv_bytes())
            self._m_prefix_hit = reg.counter(
                "ptpu_serving_prefix_hit_tokens_total",
                "prompt tokens served from shared prefix pages")
            self._m_prefix_lookup = reg.counter(
                "ptpu_serving_prefix_lookup_tokens_total",
                "prompt tokens eligible for prefix matching")
            self._m_cow = reg.counter(
                "ptpu_serving_cow_copies_total",
                "copy-on-write page copies")
            self._last_page_stats = {"prefix_hit_tokens": 0,
                                     "prefix_lookup_tokens": 0,
                                     "cow_copies": 0}
            self.peak_active_slots = 0
        if self._kv_tier is not None:
            self._m_host_pages = reg.gauge(
                "ptpu_kv_host_pages",
                "KV pages resident in the host RAM tier")
            self._m_demotions = reg.counter(
                "ptpu_kv_demotions_total",
                "cold KV pages demoted device -> host tier")
            self._m_promotions = reg.counter(
                "ptpu_kv_promotions_total",
                "tiered KV pages promoted back onto device pages")
            self._m_tier_hit = reg.counter(
                "ptpu_kv_tier_prefix_hit_tokens_total",
                "prompt tokens served from demoted prefix pages, by "
                "the tier that held them", labels=("tier",))
            self._last_page_stats.update(
                demotions=0, promotions=0,
                prefix_hit_tokens_host=0, prefix_hit_tokens_disk=0)
        if self.speculative:
            self._m_spec_acc = reg.histogram(
                "ptpu_serving_spec_accepted_length",
                "tokens emitted per row per verify step (1 = k=1 "
                "fallback or fully rejected draft), by the proposer "
                "that drafted the row ('none' = undrafted)",
                buckets=tuple(float(i) for i in
                              range(1, self.spec_k + 1)),
                labels=("proposer",))
            self._m_spec_draft = reg.counter(
                "ptpu_serving_spec_draft_tokens_total",
                "draft tokens proposed to the verify program")
            self._m_spec_accepted = reg.counter(
                "ptpu_serving_spec_accepted_draft_tokens_total",
                "draft tokens confirmed by the verify program")
            self._m_spec_hit = reg.gauge(
                "ptpu_serving_spec_draft_hit_rate",
                "cumulative accepted/proposed draft-token ratio")
            self._m_spec_proposer = reg.counter(
                "ptpu_spec_proposer_total",
                "rows drafted per verify step, by proposer kind",
                labels=("kind",))
            if self._tuner is not None:
                self._m_spec_tuner_k = reg.gauge(
                    "ptpu_spec_tuner_k",
                    "spec window k the autotuner is running per "
                    "request class (1 = speculation off)",
                    labels=("klass",))
            # host-side aggregate: the SPEC_DECODE bench line and
            # spec_stats() read this (registry histograms only keep
            # bucketized counts)
            self._spec = {"steps": 0, "gated_steps": 0, "rows": 0,
                          "emitted": 0,
                          "draft_tokens": 0, "accepted_draft_tokens": 0,
                          "draft_faults": 0, "resamples": 0,
                          "draft_s": 0.0,
                          "acc_len_hist": [0] * (self.spec_k + 1)}

    def _new_cache(self):
        """Fresh KV pool in the configured layout (init + recover).
        On a mesh engine the pools are committed SHARDED (kv_heads
        over the `model` axis) to the DECODE group, which owns all
        pool state — disaggregated prefills hand their KV over."""
        ad = self.adapter
        kv_sh = sc_sh = None
        if self.meshctx is not None:
            kv_sh = self.meshctx.kv_sharding()
            sc_sh = self.meshctx.scale_sharding()
        if self.paged:
            return PagedKVCache(
                ad.num_layers, self.max_slots, self.max_len,
                ad.kv_heads, ad.head_dim, ad.dtype,
                page_size=self.page_size, num_pages=self.num_pages,
                quant=self.kv_quant,
                prefix_sharing=self.prefix_sharing,
                kv_sharding=kv_sh, scale_sharding=sc_sh,
                tier=self._kv_tier)
        return SlotKVCache(
            ad.num_layers, self.max_slots, self.max_len,
            ad.kv_heads, ad.head_dim, ad.dtype, kv_sharding=kv_sh)

    def _refresh_state(self) -> None:
        """Re-snapshot the model weights (checkpoint loads /
        quantization on the live model take effect next step). Mesh
        engines additionally commit the snapshot to the mesh via the
        family's tp_param_spec rules — cached by source-array identity
        so an unchanged model costs no transfer — and, when
        disaggregated, keep a second placed copy on the prefill group
        (each chip group holds its own weights, the standard
        disaggregated-serving memory layout)."""
        params, buffers = self.adapter.model.raw_state()
        if self.meshctx is None:
            self._params, self._buffers = params, buffers
            return
        m = self.meshctx
        self._params, self._buffers = self._place_state(
            params, buffers, self._param_shardings(params, "decode"),
            m.repl("decode"), self._placed["decode"])
        if m.disaggregated:
            self._params_pf, self._buffers_pf = self._place_state(
                params, buffers,
                self._param_shardings(params, "prefill"),
                m.repl("prefill"), self._placed["prefill"])

    def _param_shardings(self, params, group):
        """Per-param NamedSharding dict, cached per group: static for
        a given (param-name set, mesh), so the per-step refresh only
        pays a tuple compare. A same-NAME shape change (no known
        path) would surface as a loud device_put error, never a
        silently wrong sharding."""
        key = tuple(params)
        got = self._shardings_cache.get(group)
        if got is None or got[0] != key:
            got = (key, self.meshctx.param_shardings(
                params, self.adapter, group))
            self._shardings_cache[group] = got
        return got[1]

    @staticmethod
    def _place_state(params, buffers, param_sh, repl, cache):
        fresh = {}

        def put(name, src, sh):
            got = cache.get(name)
            # identity check against the LIVE source kept in the
            # entry: a swapped array (checkpoint load) re-places even
            # if the new object reuses the old one's address
            if got is not None and got[0] is src:
                placed = got[1]
            else:
                placed = jax.device_put(src, sh)
            fresh[name] = (src, placed)
            return placed

        p = {n: put(("p", n), a, param_sh[n])
             for n, a in params.items()}
        b = {n: put(("b", n), a, repl) for n, a in buffers.items()}
        cache.clear()
        cache.update(fresh)
        return p, b

    def _publish_page_stats(self) -> None:
        if not self.paged:
            return
        c = self.cache
        self._m_pages_free.set(c.free_page_count())
        self._m_pages_active.set(c.active_page_count())
        self._m_pages_cached.set(c.cached_page_count())
        last = self._last_page_stats
        for counter, key in ((self._m_prefix_hit, "prefix_hit_tokens"),
                             (self._m_prefix_lookup,
                              "prefix_lookup_tokens"),
                             (self._m_cow, "cow_copies")):
            cur = getattr(c, key)
            if cur > last[key]:
                counter.inc(cur - last[key])
            last[key] = cur
        if self._kv_tier is not None:
            self._m_host_pages.set(self._kv_tier.host_page_count())
            for counter, key in (
                    (self._m_demotions, "demotions"),
                    (self._m_promotions, "promotions"),
                    (self._m_tier_hit.labels(tier="host"),
                     "prefix_hit_tokens_host"),
                    (self._m_tier_hit.labels(tier="disk"),
                     "prefix_hit_tokens_disk")):
                cur = getattr(c, key)
                if cur > last[key]:
                    counter.inc(cur - last[key])
                last[key] = cur

    def spec_stats(self) -> dict:
        """Speculative-decoding snapshot (raises on a non-speculative
        engine): verify steps, per-row emission totals, draft
        proposal/acceptance counts, accepted-length histogram."""
        if not self.speculative:
            raise RuntimeError("spec_stats() on a non-speculative "
                               "engine")
        s = dict(self._spec)
        s["acc_len_hist"] = list(s["acc_len_hist"])
        s["k"] = self.spec_k
        s["proposer"] = self.spec_proposer
        s["sampled"] = self.spec_sampled
        s["draft_hit_rate"] = (
            s["accepted_draft_tokens"] / s["draft_tokens"]
            if s["draft_tokens"] else 0.0)
        s["accepted_per_step"] = (
            s["emitted"] / s["rows"] if s["rows"] else 0.0)
        if self._tuner is not None:
            s["tuner"] = self._tuner.snapshot()
        return s

    def _proposer_release(self, rid: int) -> None:
        """Release one rid's draft state from EVERY configured
        proposer (the tuner may have moved a request between kinds
        mid-flight; all of them hold lockstep-evicted state)."""
        if self.speculative:
            for p in self._proposers.values():
                p.release(rid)

    def _proposer_retain(self, rids) -> None:
        if self.speculative:
            keep = list(rids)
            for p in self._proposers.values():
                p.retain(keep)

    def paged_stats(self) -> dict:
        """Paged-pool snapshot for benchmarks/dashboards (raises on a
        contiguous engine): cache page/prefix/COW counters plus the
        peak concurrent in-flight requests this engine reached."""
        if not self.paged:
            raise RuntimeError("paged_stats() on a contiguous engine")
        s = self.cache.stats()
        s["peak_active_slots"] = self.peak_active_slots
        s["prefix_hit_rate"] = (
            s["prefix_hit_tokens"] / s["prefix_lookup_tokens"]
            if s["prefix_lookup_tokens"] else 0.0)
        return s

    # -- public API ----------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 16,
               sampling: Optional[SamplingParams] = None,
               deadline_s: Optional[float] = None,
               tenant: Optional[str] = None) -> Request:
        """Queue one request; returns its handle (tokens appear on it
        as steps run).

        ``deadline_s`` (seconds from now, engine clock): the request is
        cancelled at the first step boundary past the deadline —
        ``finish_reason`` becomes ``"deadline"`` and ``Request.error``
        carries a typed :class:`DeadlineExceeded`.

        Typed refusals: :class:`EngineClosed` after ``drain()``,
        :class:`EngineBroken` until ``recover()``, :class:`QueueFull`
        when ``max_queue`` requests are already waiting.
        """
        # refuse BEFORE building: a typed refusal must not consume a
        # rid or pay input validation (submit_request re-checks for
        # callers that build first, e.g. the router)
        self._check_admission()
        return self.submit_request(self._build_request(
            prompt_ids, max_new_tokens, sampling, deadline_s,
            tenant=tenant))

    def _check_admission(self) -> None:
        if self._closed:
            raise EngineClosed()
        if self._broken:
            raise EngineBroken(self._broken)
        if self.max_queue is not None \
                and self.scheduler.depth >= self.max_queue:
            self._m_reject.labels(reason="queue_full").inc()
            raise QueueFull(self.scheduler.depth, self.max_queue)

    def _build_request(self, prompt_ids, max_new_tokens: int = 16,
                       sampling: Optional[SamplingParams] = None,
                       deadline_s: Optional[float] = None,
                       rid: Optional[int] = None,
                       tenant: Optional[str] = None) -> Request:
        """Validate inputs and build a Request WITHOUT enqueuing it.
        ``rid=None`` draws from this engine's counter; the replica
        router passes its own (globally unique across replicas, so a
        request keeps one identity through failover adoption)."""
        ids = np.asarray(getattr(prompt_ids, "numpy", lambda: prompt_ids)()
                         ).astype(np.int64)
        if ids.ndim == 2 and ids.shape[0] == 1:   # [1, T] batch-of-one
            ids = ids[0]
        if ids.ndim != 1:
            # a [B, T] batch must not silently flatten into ONE merged
            # request — submit() takes one sequence per call
            raise ValueError(
                f"submit() takes a single prompt sequence; got shape "
                f"{ids.shape}. Call submit() once per request.")
        if ids.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if ids.size + max_new_tokens - 1 > self.max_len:
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens "
                f"({max_new_tokens}) - 1 exceeds max_len {self.max_len}")
        sampling = sampling or SamplingParams()
        sampling.validate()
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0, got {deadline_s}")
        if rid is None:
            rid = self._next_rid
            self._next_rid += 1
        req = Request(rid=rid, prompt=ids,
                      max_new_tokens=int(max_new_tokens),
                      sampling=sampling,
                      deadline=(self.metrics.now() + deadline_s
                                if deadline_s is not None else None),
                      tenant=tenant)
        req._rng = np.random.RandomState(
            sampling.seed if sampling.seed is not None
            else 0x5EED + req.rid)
        return req

    def submit_request(self, req: Request) -> Request:
        """Enqueue a pre-built Request (typed admission checks apply;
        ``submit()`` is ``submit_request(_build_request(...))``)."""
        self._check_admission()
        # sampled BEFORE the request enters the queue: a request that
        # arrives while other work is in flight may see its first
        # token blocked behind prefills — the decode-stall histogram's
        # population (docs/SERVING.md "Chunked prefill")
        stalled = self.has_work()
        self.scheduler.add(req)
        self.metrics.on_submit(req.rid, stalled=stalled)
        self._m_queue_depth.set(self.scheduler.depth)
        if self.auditor is not None:
            self.auditor.on_submitted(req)
        return req

    def adopt(self, req: Request) -> Request:
        """Take over an existing request mid-flight (router failover:
        its previous replica died). The request may already carry
        delivered tokens — admission then re-prefills prompt + those
        tokens via the ``recover()`` replay contract, so greedy output
        stays token-identical and nothing is retracted. Bypasses
        ``max_queue`` (a failover must never drop a request the
        service already accepted) and does NOT re-audit submission
        (the request was audited where it first entered)."""
        if self._closed:
            raise EngineClosed()
        if self._broken:
            raise EngineBroken(self._broken)
        req.slot = None
        req.prefill_pos = None
        stalled = self.has_work()
        self.scheduler.add(req)
        self.metrics.on_submit(req.rid, stalled=stalled)
        self._m_queue_depth.set(self.scheduler.depth)
        return req

    def has_work(self) -> bool:
        return self.scheduler.has_pending() or \
            bool(self.cache.active_slots())

    def probe(self, timeout: Optional[float] = None) -> dict:
        """Health probe: a cheap, non-mutating liveness summary. The
        router calls this on every replica each round; the cluster's
        RemoteReplica turns it into one RPC with ``timeout`` as the
        per-call deadline (a slow worker surfaces as TimeoutError →
        SUSPECT, never an instant ReplicaDead). In-process, a broken
        engine is still *alive* — it answers probes and recovers — so
        this never raises."""
        del timeout  # in-process: answering at all is the liveness
        return {"broken": self._broken,
                "queued": self.scheduler.depth,
                "active": len(self.cache.active_slots())}

    def step(self) -> List[Request]:
        """One engine iteration: admit into free slots (bucketed
        prefill), then one decode step over every occupied slot, then
        evict finished sequences. Returns requests finished this step.

        Every step appends a flight-recorder record (latency, slot
        occupancy, queue depth, admissions/evictions, compile events);
        if the step raises, the recorder ring dumps to disk before the
        exception propagates — the post-mortem for a dead serving
        loop.

        Typed refusals: :class:`EngineBroken` until ``recover()`` after
        a donated-pool step failure; :class:`EngineIdle` when there is
        no queued or in-flight work (guard loops with ``has_work()``).
        """
        if self._broken:
            raise EngineBroken(self._broken)
        if not self.has_work():
            raise EngineIdle()
        t0 = self.metrics.now()
        step_idx = self._step_idx
        self._step_idx += 1
        tc0 = (self.trace_counts["decode"],
               sum(self.trace_counts["prefill"].values()))
        # the finished list is allocated HERE, outside the try: a
        # request that reaches a terminal state early in the step
        # (deadline sweep, decode finisher) is already evicted from its
        # slot/queue, so if the step then faults it exists nowhere else
        # — it must survive the raise or it is lost forever
        finished: List[Request] = []
        try:
            with span("serving.step", step=step_idx) as sp:
                admitted, n_active = self._step_inner(finished)
                sp.set_attr("active_slots", n_active)
        except Exception as e:
            if finished:
                self._undelivered.extend(finished)
            if self._donate():
                # the jit call may have CONSUMED the donated pools
                # before failing: ks/vs can reference deleted device
                # buffers, and any later step would die confusingly —
                # refuse further use until recover() rebuilds them
                self._broken = f"step #{step_idx}: " \
                               f"{type(e).__name__}: {e}"
            try:
                self.recorder.record(
                    "serving.step_error", step=step_idx,
                    error=f"{type(e).__name__}: {e}")
                path = self.recorder.dump(
                    reason=f"ServingEngine.step #{step_idx} raised "
                           f"{type(e).__name__}: {e}",
                    registry=self.registry)
                import sys
                print(f"[serving] flight recorder dumped to {path}",
                      file=sys.stderr)
            except Exception:
                pass               # never mask the original failure
            raise
        dt = self.metrics.now() - t0
        depth = self.scheduler.depth
        self._m_step.observe(dt)
        self._m_queue_depth.set(depth)
        self._m_active.set(n_active)
        wt = self._watchtower
        if wt is not None:
            wt.observe_step()
        if self._undelivered:
            # requests stranded by an earlier FAILED step ride the
            # first successful step out (they finished first: prepend)
            finished = self._undelivered + finished
        # the whole batch stays OWED until the return below actually
        # happens: if the recorder or a caller-supplied auditor raises
        # first, the next step()/recover()/drain() still delivers
        # (at worst re-auditing a prefix — detectable — never losing)
        self._undelivered = finished
        self.recorder.record(
            "serving.step", step=step_idx, step_latency_s=dt,
            active_slots=n_active, queue_depth=depth,
            admitted=admitted,
            evicted=[(r.rid, r.finish_reason) for r in finished],
            compiles_decode=self.trace_counts["decode"] - tc0[0],
            compiles_prefill=(
                sum(self.trace_counts["prefill"].values()) - tc0[1]))
        if self.auditor is not None and not self._in_drain:
            # drain() audits its aggregate return instead, so each
            # request is audited at exactly ONE external boundary
            for r in finished:
                self.auditor.on_delivered(r, via="step")
        self._undelivered = []
        return finished

    def _step_inner(self, finished: List[Request]):
        admitted: List[int] = []

        # 0) deadline + disconnect sweeps — cancel expired requests and
        # requests whose client went away BEFORE spending a prefill or
        # decode slot-step on them
        self._expire_deadlines(finished)
        self._sweep_disconnects(finished)
        # re-snapshot the weights so checkpoint loads / quantization on
        # the live model object take effect next step (same pytree
        # structure -> no retrace; the arrays are just jit arguments)
        self._refresh_state()
        # 1) admission — freed slots refill BEFORE the decode so a new
        # request's first decode token rides this very step. Paged:
        # admission is gated by FREE PAGES, not just free slots — the
        # claim reserves the request's worst-case page span so decode
        # can never run out of pages mid-flight
        claim = None
        if self.paged:
            claim = lambda req: self.cache.try_reserve(
                req, req.prompt,
                req.prompt_len + req.max_new_tokens)
        pairs = self.scheduler.admissions(
            self.cache.free_slots(), claim=claim,
            lookahead=self.admission_lookahead,
            unclaim=self.cache.cancel_reservation if self.paged
            else None)
        # per-step prefill token budget (chunked engines): one chunk's
        # worth. Prompts that fit run the MONOLITHIC prefill program
        # inside the budget (the degenerate case IS the unchunked
        # path); longer prompts claim their slot/pages now and enter
        # the PREFILLING fifo, advancing one chunk per step below —
        # so no step ever runs more than `prefill_chunk` prefill
        # tokens plus the one-token-per-slot decode.
        chunk = self.prefill_chunk
        budget = chunk
        if chunk is not None and self.chunk_control is not None:
            # adaptive budget: queued + chunk-pending work pushes it
            # up, the active-decode population (the requests every
            # extra chunk would stall) pulls it back down
            budget = self.chunk_control.step_budget(
                chunk,
                self.scheduler.depth + len(self._chunk_fifo),
                stall=float(len(self.cache.active_slots())))
        for i, (slot, req) in enumerate(pairs):
            try:
                if chunk is None:
                    self._prefill(slot, req)
                else:
                    n_ids = req.prompt_len + max(
                        0, len(req.out_tokens) - 1)
                    if not self._chunk_fifo and n_ids <= budget:
                        self._prefill(slot, req)
                        budget -= n_ids
                    else:
                        self._begin_chunked(slot, req)
            except RequestCancelled as e:
                # the client vanished while THIS request was being
                # prefilled: the abort path already unwound its pages
                # (paged) and no slot was assigned — cancel just this
                # request and keep admitting the rest of the batch
                self._finish_disconnect(req, exc=e, finished=finished)
                continue
            except Exception:
                # admissions() popped the WHOLE batch: everything not
                # yet prefilled goes back to the queue head in FCFS
                # order, or a recovered engine silently loses them
                # (their page reservations return with them)
                for _, later in reversed(pairs[i + 1:]):
                    if self.paged:
                        self.cache.cancel_reservation(later)
                    self.scheduler.requeue(later)
                if req.slot is None and not req.out_tokens:
                    if self.paged:
                        self.cache.cancel_reservation(req)
                    self.scheduler.requeue(req)
                raise
            admitted.append(req.rid)
            if req.finished:
                self._evict(slot, req, finished)
        # 1b) PREFILLING work within what is left of the step's
        # prefill budget, interleaved with the decode below. Without a
        # chunk controller this is AT MOST ONE chunk program run per
        # step (the legacy contract, bit-identical); with one, the
        # same compiled program runs back-to-back until the adaptive
        # budget is spent.
        ran = 0
        while chunk is not None and self._chunk_fifo:
            head = self.cache.slots[self._chunk_fifo[0]]
            n_ids = head.prompt_len + max(0, len(head.out_tokens) - 1)
            take = min(chunk, n_ids - head.prefill_pos)
            if take > budget:
                break
            self._chunk_step(finished)
            budget -= take
            ran += 1
            if self.chunk_control is None and ran >= 1:
                break
        if chunk is not None:
            self._m_chunk_depth.set(len(self._chunk_fifo))
        # 2) one decode step over all occupied slots — the speculative
        # engine runs its widened k-token VERIFY program instead (same
        # contract: ONE compiled program for any request mix).
        # PREFILLING slots (mid-chunked-prefill) hold no decodable
        # token yet and are skipped until their final chunk.
        active = [s for s in self.cache.active_slots()
                  if self.cache.slots[s].prefill_pos is None]
        if active:
            if self.speculative:
                self._decode_verify(active, finished)
            else:
                self._decode_plain(active, finished)
        self.metrics.on_step(len(active))
        if self.paged:
            self.peak_active_slots = max(self.peak_active_slots,
                                         len(active))
            self._publish_page_stats()
        return admitted, len(active)

    def _decode_plain(self, active, finished: List[Request]) -> None:
        """The k=1 decode step (non-speculative engines)."""
        toks = np.zeros((self.max_slots, 1), np.int64)
        pos = np.zeros((self.max_slots,), np.int32)
        mask = np.zeros((self.max_slots,), bool)
        copies = []
        for s in active:
            req = self.cache.slots[s]
            toks[s, 0] = req.out_tokens[-1]
            pos[s] = req.next_pos
            mask[s] = True
            if self.paged:
                # the write may cross into a new page (allocate)
                # or a shared one (COW) — resolve BEFORE the step
                c = self.cache.ensure_decode_page(s, req.next_pos)
                if c is not None:
                    copies.append(c)
        # COW copies run BEFORE the fault point: ensure_decode_page
        # already flipped the table rows, and a retried (non-broken)
        # step would not re-issue a lost copy — device state must be
        # consistent with the table when the fault can fire
        if self.paged:
            self._run_copies(copies)
        maybe_fail("serving.step.decode", step=self._step_idx - 1)
        if self.meshctx is not None:
            # mesh engines: the SHARDED decode program is about to run
            # (chaos kill point for the tensor-parallel flavor)
            maybe_fail("serving.decode.sharded",
                       step=self._step_idx - 1, tp=self.meshctx.tp)
        with span("serving.decode", batch=len(active),
                  request_ids=[self.cache.slots[s].rid
                               for s in active]):
            if self.paged:
                logits, ks, vs, kss, vss = self._decode_fn()(
                    self._params, self._buffers, toks, pos, mask,
                    self.cache.page_table.copy(),
                    self.cache.ks, self.cache.vs,
                    self.cache.kss, self.cache.vss)
                self.cache.ks, self.cache.vs = list(ks), list(vs)
                self.cache.kss, self.cache.vss = \
                    list(kss), list(vss)
            else:
                logits, ks, vs = self._decode_fn()(
                    self._params, self._buffers, toks, pos, mask,
                    self.cache.ks, self.cache.vs)
                self.cache.ks, self.cache.vs = list(ks), list(vs)
            logits = np.asarray(jax.device_get(logits))
        for s in active:
            req = self.cache.slots[s]
            tok = sample_token(logits[s], req.sampling, req._rng)
            req.out_tokens.append(tok)
            self.metrics.on_token(req.rid)
            if self._is_finished(req, tok):
                self._evict(s, req, finished)

    def _decode_verify(self, active, finished: List[Request]) -> None:
        """One speculative verify step: draft up to k-1 tokens per
        eligible row (n-gram prompt lookup or the small draft model,
        per the configured/tuned proposer), score all k candidate
        positions in ONE widened forward over the static cache, and
        emit the accepted prefix — for greedy rows provably the tokens
        sequential greedy decode would have produced, since each
        position's logits are computed under the identical causal mask
        and cache state; for sampled rows (spec_sampled=True) the
        rejection-sampling rule in ``_emit_verified``, which preserves
        the k=1 sampling distribution exactly (see docs/SERVING.md).

        Rows without a usable draft (no n-gram hit, sampled decoding
        without spec_sampled, tuner says off, or 1 token of budget
        left) run at per-row length 1 INSIDE the same program — the
        k=1 fallback costs no extra compile. wlen write-masks the
        PADDED lanes beyond each row's draft window; drafted-but-
        rejected tokens DO write k/v, which is safe because those
        positions sit beyond the new write position (causal-masked
        until overwritten, exactly like any stale tail) and are never
        shared/indexed — so the only rollback needed is returning
        over-allocated pages.

        A draft proposal that FAILS (fault point ``serving.spec.draft``
        or a real draft-model error) is contained to that row's step:
        the row falls back to k=1, the proposer's state for the rid is
        unwound (``_on_draft_fault``), and the step proceeds — a draft
        model must never be able to take down target decoding."""
        K = self.spec_k
        toks = np.zeros((self.max_slots, K), np.int64)
        pos = np.zeros((self.max_slots,), np.int32)
        wlen = np.zeros((self.max_slots,), np.int32)
        mask = np.zeros((self.max_slots,), bool)
        row_kind = {}          # slot -> proposer kind that DRAFTED
        row_draft = {}         # slot -> draft tokens (sampled rows)
        row_qs = {}            # slot -> per-draft q dists ([] = point mass)
        attempted = {}         # slot -> (klass, kind) fed to the tuner
        for s in active:
            req = self.cache.slots[s]
            toks[s, 0] = req.out_tokens[-1]
            pos[s] = req.next_pos
            mask[s] = True
            n = 1
            sampled = req.sampling.temperature > 0
            klass = "sampled" if sampled else "greedy"
            kind = self.spec_proposer
            k_cap = K
            if self._tuner is not None:
                k_cap, kind = self._tuner.decide(klass)
            # a draft longer than the remaining token budget is wasted
            # verify compute AND would write past the admission
            # reservation — clamp so every write stays inside the
            # request's reserved span
            budget = req.max_new_tokens - len(req.out_tokens)
            want = min(K - 1, budget - 1, k_cap - 1)
            if want > 0 and kind is not None \
                    and (not sampled or self.spec_sampled):
                prop = self._proposers[kind]
                attempted[s] = (klass, kind)
                draft, qs = (), []
                t0 = self.metrics.now()
                try:
                    maybe_fail("serving.spec.draft",
                               step=self._step_idx - 1, slot=s)
                    if sampled \
                            and isinstance(prop, DraftModelProposer):
                        draft, qs = prop.propose_sampled(
                            req.rid, req.full_ids, want,
                            req.sampling, req._rng)
                    else:
                        # point-mass proposal: q is a delta on the
                        # drafted token (qs=[] signals this to the
                        # acceptance rule)
                        draft = prop.propose(
                            req.rid, req.full_ids, want)
                except Exception as exc:
                    draft, qs = (), []
                    self._on_draft_fault(s, req, prop, exc)
                finally:
                    dt = self.metrics.now() - t0
                    self._spec["draft_s"] += dt
                    self.metrics.on_draft(dt)
                if len(draft):
                    toks[s, 1:1 + len(draft)] = draft
                    n = 1 + len(draft)
                    row_kind[s] = kind
                    if sampled:
                        row_draft[s], row_qs[s] = draft, qs
                    self._spec["draft_tokens"] += len(draft)
                    self._m_spec_draft.inc(len(draft))
                    self._m_spec_proposer.labels(kind=kind).inc()
            wlen[s] = n
        if self.spec_gate and all(int(wlen[s]) == 1 for s in active):
            # no row drafted this step: every lane would run the
            # k-wide program at wlen 1 — the k=1 decode program emits
            # the PROVABLY identical token (same logits row, same
            # per-row RNG stream for sampled rows, same page/EOS
            # bookkeeping) at 1/k the verify compute. No page state
            # was touched yet, so delegating is clean; trace counts
            # stay bounded at <= 1 decode + <= 1 verify program.
            # the mid-verify kill point still guards EVERY speculative
            # decode step (drafts considered, nothing emitted yet) —
            # gating must not thin the chaos sweep's kill cadence
            maybe_fail("serving.decode.verify",
                       step=self._step_idx - 1, gated=True)
            n_rows = len(active)
            self._decode_plain(active, finished)
            # accounting AFTER the delegated step succeeds: a fault
            # inside it replays through this gate on recover, and a
            # pre-bump would double-count rows that delivered once
            self._spec["gated_steps"] += 1
            self._spec["rows"] += n_rows
            self._spec["emitted"] += n_rows
            self._spec["acc_len_hist"][1] += n_rows
            for _ in range(n_rows):
                self._m_spec_acc.labels(proposer="none").observe(1.0)
            # rows that TRIED to draft and came back empty are signal
            # the tuner must see (accepted length 1), else an always-
            # missing proposer never reads as "not paying"
            self._tuner_step(attempted, {s: 1 for s in attempted})
            return
        copies = []
        try:
            if self.paged:
                for s in active:
                    copies += self.cache.ensure_decode_range(
                        s, self.cache.slots[s].next_pos, int(wlen[s]))
                # COW copies BEFORE the kill point (same reason as the
                # plain decode: flipped table rows must never outrun
                # their copies)
                self._run_copies(copies)
            # mid-verify-step kill point: drafts built, pages
            # claimed/COW'd, nothing emitted yet — recovery must
            # replay token-identically and leak no pages
            # (chaos-audited)
            maybe_fail("serving.decode.verify",
                       step=self._step_idx - 1)
            if self.meshctx is not None:
                maybe_fail("serving.decode.sharded",
                           step=self._step_idx - 1,
                           tp=self.meshctx.tp)
            with span("serving.verify", batch=len(active), k=K,
                      request_ids=[self.cache.slots[s].rid
                                   for s in active]):
                if self.paged:
                    logits, greedy, acc, ks, vs, kss, vss = \
                        self._verify_fn()(
                            self._params, self._buffers, toks, pos,
                            mask, wlen, self.cache.page_table.copy(),
                            self.cache.ks, self.cache.vs,
                            self.cache.kss, self.cache.vss)
                    self.cache.ks, self.cache.vs = list(ks), list(vs)
                    self.cache.kss, self.cache.vss = \
                        list(kss), list(vss)
                else:
                    logits, greedy, acc, ks, vs = self._verify_fn()(
                        self._params, self._buffers, toks, pos, mask,
                        wlen, self.cache.ks, self.cache.vs)
                    self.cache.ks, self.cache.vs = list(ks), list(vs)
                logits = np.asarray(jax.device_get(logits))
                greedy = np.asarray(jax.device_get(greedy))
                acc = np.asarray(jax.device_get(acc))
        except Exception:
            # a verify step that dies here (fault point, program
            # failure) never emitted a token, but ensure_decode_range
            # already claimed every page the k-wide write window
            # touches. Those extra pages sit past each row's next
            # write position and nothing frees them until the request
            # finishes — on a non-broken engine they silently shrink
            # the admission pool on every faulted step. Return them
            # NOW; the retried step re-claims idempotently (the page
            # holding next_pos itself is kept — the retry writes it).
            if self.paged:
                for s in active:
                    req = self.cache.slots[s]
                    if req is not None:
                        self.cache.rollback_speculation(
                            s, req.next_pos)
            raise
        emitted_by_slot = {}
        try:
            for s in active:
                req = self.cache.slots[s]
                emitted = self._emit_verified(
                    s, req, greedy[s], int(acc[s]), logits[s],
                    draft=row_draft.get(s), qs=row_qs.get(s))
                emitted_by_slot[s] = emitted
                self._spec["rows"] += 1
                self._spec["emitted"] += emitted
                self._spec["accepted_draft_tokens"] += emitted - 1
                self._spec["acc_len_hist"][min(emitted, K)] += 1
                self._m_spec_acc.labels(
                    proposer=row_kind.get(s, "none")).observe(
                        float(emitted))
                if emitted > 1:
                    self._m_spec_accepted.inc(emitted - 1)
                if self.paged and not req.finished:
                    # return pages past the next write position that
                    # only rejected draft tokens touched (finished
                    # rows release everything below)
                    self.cache.rollback_speculation(s, req.next_pos)
                if req.finished:
                    self._evict(s, req, finished)
        except Exception:
            # a fault mid-emission (serving.spec.resample) leaves rows
            # not yet emitted this pass with over-claimed pages — the
            # same debt the pre-verify except arm pays. Tokens already
            # appended stay appended (out_tokens only ever grows; the
            # retried step continues from the advanced next_pos).
            if self.paged:
                for s in active:
                    req = self.cache.slots[s]
                    if req is not None and not req.finished:
                        self.cache.rollback_speculation(
                            s, req.next_pos)
            raise
        self._spec["steps"] += 1
        if self._spec["draft_tokens"]:
            self._m_spec_hit.set(self._spec["accepted_draft_tokens"]
                                 / self._spec["draft_tokens"])
        # feed the tuner every ATTEMPTED row's accepted length (an
        # empty draft reads as 1: speculation didn't pay on that row)
        self._tuner_step(attempted,
                         {s: emitted_by_slot.get(s, 1)
                          for s in attempted})

    def _emit_verified(self, slot: int, req: Request,
                       greedy_row: np.ndarray, acc: int,
                       logits_row: np.ndarray, draft=None,
                       qs=None) -> int:
        """Apply one row's verify result: append the accepted tokens.
        Greedy rows: the first ``acc`` in-program argmax tokens,
        stopping AT an EOS exactly like sequential decode (the bitwise
        token-identity law). Undrafted sampled rows: one host-sampled
        token from position 0 — bit-identical to the k=1 path, same
        per-request RNG stream. Drafted sampled rows
        (``spec_sampled=True``): speculative REJECTION SAMPLING —
        draft j is accepted with probability min(1, p_j(t)/q_j(t))
        where p_j = sampling_dist(logits[j]) is the target
        distribution at that position and q_j the draft's (a point
        mass for n-gram drafts, ``qs[j]`` for the draft model, which
        DREW the token from exactly that q); on the first rejection
        ONE token is resampled from the normalized residual
        max(p - q, 0) and the rest of the draft is discarded; if every
        draft survives, a bonus token is sampled from the position
        AFTER the draft. By the standard speculative-sampling
        argument (Leviathan et al.) each emitted token is distributed
        EXACTLY as sequential sampling from p — the distribution-
        parity law the seed-band harness checks. Returns how many
        tokens were emitted. Factored out so the chaos pinned-red
        test can swap in a deliberately broken acceptance."""
        if req.sampling.temperature > 0:
            sp, rng = req.sampling, req._rng
            if draft is None or len(draft) == 0:
                tok = sample_token(logits_row[0], sp, rng)
                req.out_tokens.append(tok)
                self.metrics.on_token(req.rid)
                self._is_finished(req, tok)
                return 1
            emitted = 0
            for j in range(len(draft)):
                t = int(draft[j])
                p = sampling_dist(logits_row[j], sp)
                pt = float(p[t])
                qt = float(qs[j][t]) if qs else 1.0
                if qt > 0.0 and pt > 0.0 \
                        and float(rng.uniform()) < min(1.0, pt / qt):
                    req.out_tokens.append(t)
                    self.metrics.on_token(req.rid)
                    emitted += 1
                    if self._is_finished(req, t):
                        return emitted
                    continue
                # first rejection: emit ONE corrective token from the
                # residual — conditioned on rejecting q's token, the
                # residual is exactly what sequential sampling from p
                # has left (fault-point-guarded: a crash here must
                # neither lose nor duplicate tokens)
                maybe_fail("serving.spec.resample",
                           step=self._step_idx - 1, slot=slot)
                if qs:
                    res = np.maximum(p - qs[j], 0.0)
                else:
                    res = p.copy()
                    res[t] = 0.0
                tot = res.sum()
                # q >= p everywhere means rejection was measure-zero
                # (float dust): fall back to p itself
                res = p if tot <= 0.0 else res / tot
                tok = int(rng.choice(res.size, p=res))
                req.out_tokens.append(tok)
                self.metrics.on_token(req.rid)
                emitted += 1
                self._spec["resamples"] += 1
                self._is_finished(req, tok)
                return emitted
            # every draft accepted: the verify pass already computed
            # the next position's logits — the classic free bonus
            tok = sample_token(logits_row[len(draft)], sp, rng)
            req.out_tokens.append(tok)
            self.metrics.on_token(req.rid)
            emitted += 1
            self._is_finished(req, tok)
            return emitted
        emitted = 0
        for j in range(acc):
            tok = int(greedy_row[j])
            req.out_tokens.append(tok)
            self.metrics.on_token(req.rid)
            emitted += 1
            if self._is_finished(req, tok):
                # sequential decode stops AT the EOS — accepted
                # tokens beyond it must not surface
                break
        return emitted

    def _on_draft_fault(self, slot: int, req: Request, proposer,
                        exc: Exception) -> None:
        """Contain a failed draft proposal to one row of one step: the
        row falls back to k=1 and the proposer's state for this rid is
        unwound (next step re-derives it from confirmed history). A
        REAL draft-model failure may have died with donated pools in
        flight, so the draft proposer's whole pool is reset — the same
        poisoned-donation reasoning as ``recover()``, scoped to the
        draft side. Factored out (like ``_emit_verified``) so the
        chaos pinned-red test can re-introduce the pre-fix shape
        (request-fatal draft faults) and prove the conservation ledger
        catches it."""
        if isinstance(exc, InjectedFault) \
                or not isinstance(proposer, DraftModelProposer):
            proposer.unwind(req.rid)
        else:
            proposer.reset()
        self._spec["draft_faults"] += 1

    def _tuner_step(self, attempted: dict, accepted: dict) -> None:
        """Feed one verify step's accepted lengths to the autotuner
        and advance its clock + gauges (no-op without spec_tune)."""
        if self._tuner is None:
            return
        for s, (klass, kind) in attempted.items():
            self._tuner.observe(klass, kind, accepted.get(s, 1))
        self._tuner.on_step()
        snap = self._tuner.snapshot()
        for klass, st in snap["classes"].items():
            self._m_spec_tuner_k.labels(klass=klass).set(st["k"])

    def _evict(self, slot: int, req: Request,
               finished: List[Request]) -> None:
        # a PREFILLING request can reach a terminal state mid-chunked-
        # prefill (deadline, disconnect, drain cutoff): drop its chunk
        # bookkeeping so release() below is the whole cleanup
        self._clear_chunk_state(slot, req)
        self.cache.release(slot)
        req.slot = None
        finished.append(req)
        self._m_evict.labels(reason=req.finish_reason or "unknown").inc()
        self.metrics.on_finished(req.rid)
        self._proposer_release(req.rid)

    def _expire_deadlines(self, finished: List[Request]) -> None:
        """Cancel queued and in-flight requests past their deadline
        (step-boundary sweep; XLA steps are not interruptible
        mid-kernel, so the boundary is the cancellation grain)."""
        now = self.metrics.now()
        for req in self.scheduler.expire(now):
            req.finished, req.finish_reason = True, "deadline"
            req.error = DeadlineExceeded(
                req.rid, "expired while queued")
            self._m_deadline.inc()
            self.metrics.on_finished(req.rid)
            finished.append(req)
        for s in self.cache.active_slots():
            req = self.cache.slots[s]
            if req.deadline is not None and now > req.deadline:
                req.finished, req.finish_reason = True, "deadline"
                req.error = DeadlineExceeded(
                    req.rid, f"expired in slot {s} after "
                             f"{len(req.out_tokens)} token(s)")
                self._m_deadline.inc()
                self._evict(s, req, finished)

    def _cancel_requested(self, req: Request) -> bool:
        """True if the client behind ``req`` is known gone: either the
        request's own flag (set by the front door, possibly from an
        HTTP thread) or the installed ``cancel_probe``. A probe that
        itself dies must never take the engine down — it just reads
        as 'still connected'."""
        if req.cancel_requested:
            return True
        probe = self.cancel_probe
        if probe is None:
            return False
        try:
            if probe(req):
                req.cancel_requested = True
                return True
        except Exception:
            return False
        return False

    def _finish_disconnect(self, req: Request,
                           detail: Optional[str] = None,
                           exc: Optional[BaseException] = None,
                           finished: Optional[List[Request]] = None) \
            -> None:
        """Terminal bookkeeping shared by every path that observes the
        client gone (prefill abort, queued/slot sweeps, recover): one
        place to keep the disconnect state/metric story consistent.
        Callers that evict a slot pass ``finished=None`` and let
        ``_evict`` do the delivery accounting."""
        req.finished, req.finish_reason = True, "disconnect"
        req.error = exc if exc is not None \
            else RequestCancelled(req.rid, detail or "disconnect")
        self._m_disconnect.inc()
        if finished is not None:
            self.metrics.on_finished(req.rid)
            finished.append(req)

    def _sweep_disconnects(self, finished: List[Request]) -> None:
        """Cancel queued and in-flight requests whose client went away
        (same step-boundary grain as the deadline sweep); freed slots
        return their KV pages via the normal release path."""
        if self.cancel_probe is None and \
                not any(r.cancel_requested
                        for r in self.scheduler.pending()) and \
                not any(self.cache.slots[s].cancel_requested
                        for s in self.cache.active_slots()):
            return
        for req in list(self.scheduler.pending()):
            if self._cancel_requested(req):
                self.scheduler.remove(req)
                self._finish_disconnect(
                    req, "client disconnected while queued",
                    finished=finished)
        for s in self.cache.active_slots():
            req = self.cache.slots[s]
            if self._cancel_requested(req):
                self._finish_disconnect(
                    req, f"client disconnected in slot {s} after "
                         f"{len(req.out_tokens)} token(s)")
                self._evict(s, req, finished)

    def cancel(self, req: Request, reason: str = "cancelled") -> bool:
        """Cancel one request (queued or in-flight); returns False if
        it already finished. Delivered tokens stay on the handle."""
        if req.finished:
            return False
        if self.scheduler.remove(req):
            pass
        elif req.slot is not None \
                and self.cache.slots[req.slot] is req:
            self._clear_chunk_state(req.slot, req)
            self.cache.release(req.slot)
            req.slot = None
            self._m_evict.labels(reason=reason).inc()
        else:
            return False
        req.finished, req.finish_reason = True, reason
        req.error = RequestCancelled(req.rid, reason)
        self.metrics.on_finished(req.rid)
        self._proposer_release(req.rid)
        if self.auditor is not None:
            self.auditor.on_delivered(req, via="cancel")
        return True

    def recover(self) -> dict:
        """Rebuild device state from host-side request state after a
        failed step, instead of abandoning the engine.

        Fresh KV pools are allocated (the old ones may reference
        deleted device buffers after donation), every in-flight request
        is re-prefilled over its prompt + already-delivered tokens
        (positions ``0..next_pos-1``), and decoding resumes exactly
        where it stopped. For greedy requests the re-prefill logits
        re-predict the last delivered token — verified and counted in
        ``ptpu_serving_recover_replay_mismatch_total`` (delivered
        tokens are never retracted). Safe to call repeatedly: a fault
        during recovery leaves the engine broken and the next
        ``recover()`` starts over from the same host state.

        Returns a report: recovered slot count, replay mismatches,
        latency, finished requests that were evicted (they completed
        in the failed step but were never returned).
        """
        t0 = self.metrics.now()
        reason = self._broken
        in_flight = [(s, r) for s, r in enumerate(self.cache.slots)
                     if r is not None]
        # chunked-prefill state dies with the old pools: recovery
        # re-prefills every in-flight request MONOLITHICALLY (the
        # re-prefill program writes the whole span in one pass, which
        # is the chunked path's degenerate case — token-identical);
        # fresh admissions after recovery re-chunk normally
        self._chunk_fifo.clear()
        self._chunk_local.clear()
        for _, r in in_flight:
            r.prefill_pos = None
        if self.paged:
            # flush the dying pool's counter deltas, then re-baseline:
            # the fresh pool restarts its raw counters at zero and a
            # stale baseline would swallow all increments after this
            self._publish_page_stats()
            self._last_page_stats = {k: 0
                                     for k in self._last_page_stats}
        # staged promotions die with the old pools; the tier itself
        # SURVIVES — _new_cache() rehydrates its radix index from the
        # tier, so demoted prefixes stay warm across the rebuild
        self._staged_promotions.clear()
        self.cache = self._new_cache()
        self._refresh_state()
        # accumulate on the ENGINE, not a local: if a re-prefill below
        # faults, these requests are gone from the slot table, and the
        # retrying recover() must still deliver them in its report.
        # _undelivered also carries requests a FAILED step finished but
        # never returned (same conservation debt, same payoff point).
        finished = self._undelivered
        todo = []
        for s, req in in_flight:
            if req.finished:
                # completed inside the failed step, never delivered:
                # evict now and hand it back via the report
                req.slot = None
                self._m_evict.labels(
                    reason=req.finish_reason or "unknown").inc()
                self.metrics.on_finished(req.rid)
                finished.append(req)
            else:
                # re-assign bookkeeping FIRST so a fault mid-re-prefill
                # leaves the slot table complete and recover() can
                # simply run again
                self.cache.assign(s, req)
                todo.append((s, req))
        mismatches = 0
        for s, req in todo:
            if self._cancel_requested(req):
                # the client vanished while the engine was down: don't
                # pay a re-prefill nobody is listening to
                self.cache.release(s)
                req.slot = None
                self._finish_disconnect(
                    req, "client disconnected during recover()",
                    finished=finished)
                continue
            if not req.out_tokens:
                # the failed step died between slot assignment and the
                # first sampled token: finish the prefill now
                logits = self._prefill_raw(s, req.prompt,
                                           request_id=req.rid,
                                           req=req)
                tok = sample_token(logits, req.sampling, req._rng)
                req.out_tokens.append(tok)
                self.metrics.on_token(req.rid)
                if self._is_finished(req, tok):
                    self._evict(s, req, finished)
                continue
            ids = req.prompt if len(req.out_tokens) <= 1 else \
                np.concatenate([req.prompt,
                                np.asarray(req.out_tokens[:-1],
                                           np.int64)])
            logits = self._prefill_raw(s, ids, request_id=req.rid,
                                       req=req)
            if req.sampling.temperature <= 0 \
                    and int(np.argmax(logits)) != req.out_tokens[-1]:
                mismatches += 1
                self._m_replay_mismatch.inc()
        if self.speculative:
            # prune draft-proposer state to the requests that survived
            # into the rebuilt slot table (a finished/disconnected
            # request's index must not outlive it — the no-leak law);
            # EVERY configured proposer prunes, not just the active one
            self._proposer_retain(
                r.rid for r in self.cache.slots if r is not None)
        self._broken = None
        self._m_recover.inc()
        dt = self.metrics.now() - t0
        report = {"reason": reason,
                  "recovered_slots": len(todo),
                  "replay_mismatches": mismatches,
                  "finished": list(finished),
                  "latency_s": dt}
        self.recorder.record(
            "serving.recover", reason=reason, latency_s=dt,
            recovered_slots=len(todo), replay_mismatches=mismatches,
            evicted=[(r.rid, r.finish_reason) for r in finished])
        if self.auditor is not None:
            for r in report["finished"]:
                self.auditor.on_delivered(r, via="recover")
        # consumed only once the report is actually on its way to the
        # caller: a recorder/auditor raise above leaves the debt in
        # place for the next step()/recover() instead of losing it
        self._undelivered = []
        return report

    def inflight_rids(self) -> set:
        """Every request id the engine itself still owns: queued,
        decoding in a slot, staged mid-handoff/promotion, or finished
        but not yet delivered. The complement of this set against
        ``metrics.inflight_phases()`` is watchtower's orphan detector:
        a rid the metrics ledger tracks that appears in none of these
        places has been dropped by a fault that unwound the engine's
        bookkeeping but never requeued or finished the request."""
        rids = {r.rid for r in self.scheduler.pending()}
        for s in self.cache.active_slots():
            rids.add(self.cache.slots[s].rid)
        rids.update(r.rid for r in self._undelivered)
        rids.update(self._staged_handoffs)
        rids.update(self._staged_promotions)
        return rids

    def run(self, max_steps: Optional[int] = None) -> List[Request]:
        """Drive step() until the queue and every slot drain."""
        done: List[Request] = []
        steps = 0
        while self.has_work():
            done.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return done

    def drain(self, max_steps: Optional[int] = None) -> List[Request]:
        """Graceful shutdown: refuse new submissions (submit() raises
        :class:`EngineClosed` from now on) and serve the queue plus
        every in-flight slot to completion. If ``max_steps`` runs out
        first — or the engine is (or becomes) broken and the caller
        chose shutdown over ``recover()``, or steps keep failing —
        whatever remains is cancelled (``finish_reason ==
        "cancelled"``) instead of being stranded un-finished. Returns
        every request finished or cancelled during the drain.

        drain() never raises out of the step loop: a mid-drain step
        exception must not discard the already-finished ``done`` list.
        A transient step failure (engine not broken: the faulted
        request was re-queued) is retried; after ``_DRAIN_MAX_FAILURES``
        consecutive failures the remainder is cancelled with the last
        error attached, and ``done`` is returned intact."""
        self._closed = True
        done: List[Request] = []
        steps = 0
        failures = 0
        last_err: Optional[BaseException] = None
        self._in_drain = True
        try:
            while self.has_work():
                if max_steps is not None and steps >= max_steps:
                    cutoff = "drain cutoff"
                elif self._broken:
                    cutoff = f"drain on broken engine ({self._broken})"
                elif failures >= self._DRAIN_MAX_FAILURES:
                    cutoff = (f"drain aborted after {failures} "
                              f"consecutive step failures "
                              f"({type(last_err).__name__}: {last_err})")
                else:
                    cutoff = None
                if cutoff is not None:
                    for req in self.scheduler.drain():
                        req.finished, req.finish_reason = \
                            True, "cancelled"
                        req.error = RequestCancelled(req.rid, cutoff)
                        self.metrics.on_finished(req.rid)
                        done.append(req)
                    for s in self.cache.active_slots():
                        req = self.cache.slots[s]
                        req.finished, req.finish_reason = \
                            True, "cancelled"
                        req.error = RequestCancelled(req.rid, cutoff)
                        self._evict(s, req, done)
                    break
                try:
                    done.extend(self.step())
                    steps += 1
                    failures = 0
                except Exception as e:
                    # the failed step's own finishers sit in
                    # _undelivered (see step()); the next loop pass
                    # either retries, or the cutoff collects them below
                    failures += 1
                    last_err = e
        finally:
            self._in_drain = False
        if self._undelivered:
            # terminal requests stranded by a failed step with no
            # successful step left to carry them out
            done.extend(self._undelivered)
        self._proposer_retain(())          # drained engine holds none
        # owe the whole return until it happens: if the auditor raises
        # here, a re-issued drain() flushes the debt to the caller
        self._undelivered = done
        if self.auditor is not None:
            for r in done:
                self.auditor.on_delivered(r, via="drain")
        self._undelivered = []
        return done

    # consecutive failed steps a drain() absorbs before giving up on
    # serving the backlog and cancelling the remainder
    _DRAIN_MAX_FAILURES = 3

    # -- internals -----------------------------------------------------
    def _is_finished(self, req: Request, tok: int) -> bool:
        if self.eos_id is not None and tok == self.eos_id:
            req.finished, req.finish_reason = True, "eos"
        elif len(req.out_tokens) >= req.max_new_tokens:
            req.finished, req.finish_reason = True, "length"
        return req.finished

    def _prefill(self, slot: int, req: Request) -> None:
        """Run the bucketed prefill program for one request, write its
        k/v into the slot row, and sample its first token (TTFT).

        A request adopted mid-flight (router failover: it already
        carries delivered tokens) re-prefills prompt + those tokens
        instead — the ``recover()`` replay contract: greedy replay
        re-predicts the last delivered token (mismatches counted,
        tokens never retracted) and decode resumes where it stopped."""
        self.metrics.on_first_prefill(req.rid)   # queue wait ends here
        if req.out_tokens:
            ids = req.prompt if len(req.out_tokens) <= 1 else \
                np.concatenate([req.prompt,
                                np.asarray(req.out_tokens[:-1],
                                           np.int64)])
            logits = self._prefill_raw(slot, ids, request_id=req.rid,
                                       req=req, cancel_check=True)
            self.cache.assign(slot, req)
            req.slot = slot
            if req.sampling.temperature <= 0 \
                    and int(np.argmax(logits)) != req.out_tokens[-1]:
                self._m_replay_mismatch.inc()
            return
        logits = self._prefill_raw(slot, req.prompt,
                                   request_id=req.rid, req=req,
                                   cancel_check=True)
        self.cache.assign(slot, req)
        req.slot = slot
        tok = sample_token(logits, req.sampling, req._rng)
        req.out_tokens.append(tok)
        self.metrics.on_token(req.rid)
        self._is_finished(req, tok)

    def _prefill_raw(self, slot: int, ids: np.ndarray,
                     request_id=None, req=None,
                     cancel_check: bool = False) -> np.ndarray:
        """Write ``ids``'s k/v into positions ``0..len-1`` of the slot
        row via the bucketed prefill program and return the host
        logits at the last real token. Shared by admission prefill and
        ``recover()``'s re-prefill (which replays prompt + delivered
        tokens through the same program).

        Paged: the prompt is first matched against the prefix index —
        matched pages are referenced instead of recomputed and only
        the tail runs through a prefill program (the full-prompt
        program when nothing matched, the paged EXTEND program — which
        attends over the shared pages — otherwise). A failure after
        pages were claimed unwinds them (abort_sequence)."""
        maybe_fail("serving.step.prefill", slot=slot)
        n = int(ids.shape[0])
        disagg = self.meshctx is not None \
            and self.meshctx.disaggregated
        if not self.paged:
            if cancel_check and req is not None \
                    and self._cancel_requested(req):
                # disconnect observed before the prefill program runs
                # (the paged path checks AFTER pages are claimed, so
                # the abort path is what gets exercised there)
                raise RequestCancelled(
                    req.rid, "client disconnected before prefill")
            bucket = bucket_for(n, self.min_bucket, self.max_len)
            self._m_prefill.labels(bucket=bucket).inc()
            with span("serving.prefill", request_id=request_id,
                      slot=slot, bucket=bucket, prompt_len=n,
                      replay=bool(req is not None and req.out_tokens)):
                padded = np.zeros((1, bucket), np.int64)
                padded[0, :n] = ids
                if disagg:
                    # compute on the PREFILL group, then hand the
                    # finished rows to the decode-owned pool; a
                    # failed handoff's staged span dies with this
                    # frame (the contiguous pool has no page claims
                    # to unwind — the slot was never assigned)
                    logits, kb, vb = self._prefill_fn()(
                        self._params_pf, self._buffers_pf, padded,
                        np.int32(n))
                    try:
                        self._kv_handoff(req, slot, (kb, vb),
                                         cancel_check=cancel_check)
                    except Exception:
                        if req is not None:
                            self._staged_handoffs.pop(req.rid, None)
                        raise
                else:
                    logits, ks, vs = self._prefill_fn()(
                        self._params, self._buffers, padded,
                        np.int32(n), np.int32(slot),
                        self.cache.ks, self.cache.vs)
                    self.cache.ks, self.cache.vs = list(ks), list(vs)
            return np.asarray(jax.device_get(logits))
        cache = self.cache
        try:
            if req.rid not in cache._plans:
                # admission reserves at claim time; recover()'s
                # re-prefill reserves here (a fresh pool always fits
                # what it held). Inside the unwind scope: a failure
                # here routes through abort_sequence, which no-ops on
                # a missing plan
                if not cache.try_reserve(req, ids,
                                         req.prompt_len
                                         + req.max_new_tokens):
                    raise RuntimeError(
                        f"request {req.rid}: page reservation failed "
                        f"on re-prefill (pool too small for "
                        f"in-flight set)")
            # same-wave sharing: earlier admissions in THIS batch have
            # registered their pages since the claim — re-match now
            cache.refresh_reservation(req, ids)
            start, copies = cache.begin_sequence(slot, req, ids)
            # mid-prefill fault point: pages are claimed, the table
            # row is live, nothing has run on device yet — the abort
            # path below must return every page (chaos-audited)
            maybe_fail("serving.prefill.paged", slot=slot,
                       shared=start > 0)
            if cancel_check and self._cancel_requested(req):
                # disconnect landed MID-prefill: pages are claimed and
                # the table row is live — raising here routes through
                # abort_sequence below, which must return every page
                # (pinned by the page-leak chaos law)
                raise RequestCancelled(
                    req.rid, "client disconnected mid-prefill")
            self._run_copies(copies)
            # promoted host/disk pages install BEFORE the extend
            # program attends over them (staged; unwinds on fault)
            self._stage_promotions(req, slot)
            tail = n - start
            bucket = bucket_for(tail, self.min_bucket, self.max_len)
            self._m_prefill.labels(bucket=bucket).inc()
            with span("serving.prefill", request_id=request_id,
                      slot=slot, bucket=bucket, prompt_len=n,
                      shared_prefix=start,
                      replay=bool(req.out_tokens)):
                padded = np.zeros((1, bucket), np.int64)
                padded[0, :tail] = ids[start:]
                row = cache.page_table[slot]
                if start == 0 and disagg:
                    # full prefill on the PREFILL group; the page
                    # blocks (int8-quantized there when configured)
                    # hand off to the decode pool at the claimed ids
                    npages = (bucket + cache.page_size - 1) \
                        // cache.page_size
                    logits, kb, vb, ksb, vsb = self._prefill_fn()(
                        self._params_pf, self._buffers_pf, padded,
                        np.int32(n))
                    self._kv_handoff(req, slot, (kb, vb, ksb, vsb),
                                     page_ids=row[:npages].copy(),
                                     cancel_check=cancel_check)
                elif start == 0:
                    npages = (bucket + cache.page_size - 1) \
                        // cache.page_size
                    logits, ks, vs, kss, vss = self._prefill_fn()(
                        self._params, self._buffers, padded,
                        np.int32(n), row[:npages].copy(),
                        cache.ks, cache.vs, cache.kss, cache.vss)
                    cache.ks, cache.vs = list(ks), list(vs)
                    cache.kss, cache.vss = list(kss), list(vss)
                else:
                    # prefix-hit EXTEND: stays on the decode group —
                    # it attends over shared pages already resident
                    # in the decode-owned pool
                    logits, ks, vs, kss, vss = self._extend_fn()(
                        self._params, self._buffers, padded,
                        np.int32(start), np.int32(tail), row.copy(),
                        cache.ks, cache.vs, cache.kss, cache.vss)
                    cache.ks, cache.vs = list(ks), list(vs)
                    cache.kss, cache.vss = list(kss), list(vss)
            cache.register_prefix(slot, ids)
            return np.asarray(jax.device_get(logits))
        except Exception:
            # the cross-group unwind: drop the staged prefill-side
            # span (if a handoff was in flight) AND any staged
            # promotion WITH the decode-side page claims — the leak
            # audit checks every half
            self._staged_handoffs.pop(req.rid, None)
            self._staged_promotions.pop(req.rid, None)
            cache.abort_sequence(slot, req)
            raise

    # -- chunked prefill ----------------------------------------------
    @staticmethod
    def _replay_ids(req: Request) -> np.ndarray:
        """The token span a (re-)prefill writes: the prompt, plus all
        but the last delivered token for adopted/replayed requests
        (the last token is re-predicted by the final logits — the
        recover() replay contract)."""
        return req.prompt if len(req.out_tokens) <= 1 else \
            np.concatenate([req.prompt,
                            np.asarray(req.out_tokens[:-1], np.int64)])

    def _begin_chunked(self, slot: int, req: Request) -> None:
        """Claim a slot for a CHUNKED prefill without running any
        compute: the request enters the PREFILLING state (slot leased,
        pages placed, ``prefill_pos`` at the shared-prefix boundary)
        and advances one chunk per step from the fifo head
        (``_chunk_step``). Paged admission already committed the
        worst-case page reservation at claim time, so chunking can
        never run out of pages mid-prompt."""
        self.metrics.on_first_prefill(req.rid)   # queue wait ends here
        ids = self._replay_ids(req)
        start = 0
        if self.paged:
            cache = self.cache
            try:
                if req.rid not in cache._plans:
                    # inside the unwind scope (abort_sequence no-ops
                    # on a missing plan), so a reservation that fails
                    # halfway can never strand its pinned pages
                    if not cache.try_reserve(req, ids,
                                             req.prompt_len
                                             + req.max_new_tokens):
                        raise RuntimeError(
                            f"request {req.rid}: page reservation "
                            f"failed at chunked admission")
                cache.refresh_reservation(req, ids)
                start, copies = cache.begin_sequence(slot, req, ids)
                self._run_copies(copies)
                self._stage_promotions(req, slot)
            except Exception:
                # pages claimed but the slot never assigned: the
                # standard abort path returns every claim, and the
                # caller (_step_inner) requeues the request
                self._staged_promotions.pop(req.rid, None)
                cache.abort_sequence(slot, req)
                raise
        self.cache.assign(slot, req)
        req.slot = slot
        req.prefill_pos = int(start)
        self._chunk_fifo.append(slot)
        if self._params_pf is not None and \
                (not self.paged or start == 0):
            # disaggregated: chunks accumulate in local buffers on the
            # PREFILL group; the final span hands off to the decode
            # pool. Paged prefix-hit admissions (start > 0) instead
            # chunk through the decode-group program, like extends —
            # they attend over shared pages resident in that pool.
            self._chunk_local[req.rid] = self._new_chunk_local()

    def _new_chunk_local(self):
        """Fresh per-layer [1, max_len] KV buffers on the prefill
        group (zeros: never-written tails stay finite, and the causal
        mask zeroes their softmax weight exactly)."""
        ad = self.adapter
        shape = (1, self.max_len, ad.kv_heads, ad.head_dim)
        sh = self.meshctx.kv_sharding("prefill")
        mk = lambda: [jax.device_put(jnp.zeros(shape, ad.dtype), sh)
                      for _ in range(ad.num_layers)]
        return mk(), mk()

    def _chunk_step(self, finished: List[Request]) -> None:
        """Advance the PREFILLING fifo head by one chunk: write chunk
        tokens ``prefill_pos .. prefill_pos + t - 1`` into the slot's
        KV (attending over everything already written — bitwise what
        the monolithic prefill computed for the same positions), and
        on the FINAL chunk sample the first token and enter decode."""
        slot = self._chunk_fifo[0]
        req = self.cache.slots[slot]
        ids = self._replay_ids(req)
        n = int(ids.shape[0])
        pos = req.prefill_pos
        t = min(self.prefill_chunk, n - pos)
        final = pos + t >= n
        try:
            # mid-chunk fault point: slot leased, pages claimed, part
            # of the prompt already written — the unwind below must
            # free pages AND the lease and requeue (chaos-audited)
            maybe_fail("serving.prefill.chunk", slot=slot, pos=pos,
                       final=final)
            if self._cancel_requested(req):
                raise RequestCancelled(
                    req.rid, "client disconnected mid-chunked-prefill")
            bucket = bucket_for(t, self.min_bucket, self.max_len)
            self._m_prefill.labels(bucket=bucket).inc()
            with span("serving.chunk_prefill", request_id=req.rid,
                      slot=slot, pos=pos, chunk=t, final=final,
                      replay=bool(req.out_tokens)):
                padded = np.zeros((1, bucket), np.int64)
                padded[0, :t] = ids[pos:pos + t]
                logits = self._run_chunk(slot, req, padded, pos, t,
                                         final, ids)
        except RequestCancelled as e:
            self._unwind_chunk(slot, req, requeue=False)
            self._finish_disconnect(req, exc=e, finished=finished)
            return
        except Exception:
            self._unwind_chunk(slot, req, requeue=True)
            raise
        req.prefill_pos = pos + t
        self._m_chunk_steps.inc()
        if final:
            self._finish_chunked(slot, req, ids, logits, finished)

    def _run_chunk(self, slot: int, req: Request, padded, pos: int,
                   t: int, final: bool, ids) -> np.ndarray:
        """Run one chunk program in the layout/mesh-appropriate
        flavor and return the host logits at the chunk's last real
        token (only the FINAL chunk's logits are consumed)."""
        if req.rid in self._chunk_local:
            # disaggregated local-buffer mode (contiguous, or paged
            # full prefill): compute on the prefill group; the final
            # span ships through the _kv_handoff staging contract
            logits = self._chunk_local_run(req, padded, pos, t)
            if final:
                if self.paged:
                    self._chunk_finalize_handoff(slot, req,
                                                 int(ids.shape[0]))
                else:
                    kb, vb = self._chunk_local[req.rid]
                    self._kv_handoff(req, slot, (kb, vb))
            return logits
        cache = self.cache
        if self.paged:
            row = cache.page_table[slot]
            logits, ks, vs, kss, vss = self._chunk_fn()(
                self._params, self._buffers, padded,
                np.int32(pos), np.int32(t), row.copy(),
                cache.ks, cache.vs, cache.kss, cache.vss)
            cache.ks, cache.vs = list(ks), list(vs)
            cache.kss, cache.vss = list(kss), list(vss)
        else:
            logits, ks, vs = self._chunk_fn()(
                self._params, self._buffers, padded,
                np.int32(pos), np.int32(t), np.int32(slot),
                cache.ks, cache.vs)
            cache.ks, cache.vs = list(ks), list(vs)
        return np.asarray(jax.device_get(logits))

    def _chunk_local_run(self, req: Request, padded, pos: int,
                         t: int) -> np.ndarray:
        kb, vb = self._chunk_local[req.rid]
        logits, kb2, vb2 = self._chunk_local_fn()(
            self._params_pf, self._buffers_pf, padded,
            np.int32(pos), np.int32(t), kb, vb)
        self._chunk_local[req.rid] = (list(kb2), list(vb2))
        return np.asarray(jax.device_get(logits))

    def _chunk_finalize_handoff(self, slot: int, req: Request,
                                n: int) -> None:
        """Paged disaggregated final chunk: paginate (and int8-
        quantize, when configured) the accumulated local buffers and
        install them at the claimed page ids via the standard KV
        handoff."""
        cache = self.cache
        bucket = bucket_for(n, self.min_bucket, self.max_len)
        npg = (bucket + cache.page_size - 1) // cache.page_size
        kb, vb = self._chunk_local[req.rid]
        blocks = self._chunk_fin_fn(npg)(kb, vb)
        row = cache.page_table[slot]
        self._kv_handoff(req, slot, blocks,
                         page_ids=row[:npg].copy())

    def _finish_chunked(self, slot: int, req: Request, ids,
                        logits: np.ndarray,
                        finished: List[Request]) -> None:
        """Final chunk done: leave the PREFILLING state and enter
        decode (or, on a replay, verify the re-predicted token) —
        exactly what the tail of the monolithic ``_prefill`` does."""
        self._chunk_fifo.pop(0)
        req.prefill_pos = None
        self._chunk_local.pop(req.rid, None)
        if self.paged:
            self.cache.register_prefix(slot, ids)
        if req.out_tokens:
            if req.sampling.temperature <= 0 \
                    and int(np.argmax(logits)) != req.out_tokens[-1]:
                self._m_replay_mismatch.inc()
            return
        tok = sample_token(logits, req.sampling, req._rng)
        req.out_tokens.append(tok)
        self.metrics.on_token(req.rid)
        if self._is_finished(req, tok):
            self._evict(slot, req, finished)

    def _clear_chunk_state(self, slot: int, req: Request) -> None:
        """Drop a PREFILLING request's chunk bookkeeping (fifo entry,
        local buffers, staged handoff) WITHOUT touching the cache —
        the terminal paths (_evict, cancel) release the slot
        themselves."""
        if req.prefill_pos is None:
            return
        req.prefill_pos = None
        if slot in self._chunk_fifo:
            self._chunk_fifo.remove(slot)
        self._chunk_local.pop(req.rid, None)
        self._staged_handoffs.pop(req.rid, None)

    def _unwind_chunk(self, slot: int, req: Request,
                      requeue: bool) -> None:
        """Unwind a PREFILLING slot after a mid-chunk fault or
        cancel: chunk bookkeeping dies, the paged claims return via
        the standard abort path, and the lease frees (abort_sequence
        zeroed the table row and popped the plan, so release() has
        nothing left to double-unref). ``requeue`` puts the request
        back at the queue head — its replay re-chunks
        token-identically."""
        self._clear_chunk_state(slot, req)
        if self.paged:
            self.cache.abort_sequence(slot, req)
        self.cache.release(slot)
        req.slot = None
        if requeue:
            self.scheduler.requeue(req)

    def _run_copies(self, copies) -> None:
        """Run COW page copies on device (host-picked src/dst, one
        tiny compiled program reused for every copy)."""
        for src, dst in copies:
            c = self.cache
            out = self._copy_fn()(np.int32(src), np.int32(dst),
                                  c.ks, c.vs, c.kss, c.vss)
            c.ks, c.vs = list(out[0]), list(out[1])
            c.kss, c.vss = list(out[2]), list(out[3])

    def _prog_shardings(self, group: str = "decode"):
        """Sharding trees for jitting one engine program under the
        mesh: (params dict, buffers dict, replicated, per-layer KV
        pool list, per-layer scale list — empty when not int8)."""
        m, ad = self.meshctx, self.adapter
        L = ad.num_layers
        params = self._params if group == "decode" else self._params_pf
        bufs = self._buffers if group == "decode" else self._buffers_pf
        return (self._param_shardings(params, group),
                m.replicated_tree(bufs, group),
                m.repl(group),
                [m.kv_sharding(group)] * L,
                [m.scale_sharding(group)] * L
                if (self.paged and self.kv_quant) else [])

    def _paged_caches(self, ks, vs, kss, vss, table, pos, wlen=None):
        """Per-layer paged cache tuples for the model forward
        (scales None on the model-dtype path; ``wlen`` appends the
        per-row write-length element — the speculative verify
        7-tuple flavor)."""
        tail = (wlen,) if wlen is not None else ()
        return [(k, v, kss[i] if kss else None,
                 vss[i] if vss else None, table, pos) + tail
                for i, (k, v) in enumerate(zip(ks, vs))]

    @staticmethod
    def _unpack_paged(new_caches):
        d = lambda x: getattr(x, "_data", x)
        ks2 = [d(c[0]) for c in new_caches]
        vs2 = [d(c[1]) for c in new_caches]
        kss2 = [d(c[2]) for c in new_caches] \
            if new_caches[0][2] is not None else []
        vss2 = [d(c[3]) for c in new_caches] \
            if new_caches[0][3] is not None else []
        return ks2, vs2, kss2, vss2

    def _prefill_fn(self):
        """Full-prompt prefill program, one compile per bucket length:
        run the prompt through a local [1, bucket] static cache, take
        the logits at the LAST REAL token (the bucket tail is
        padding), and splice the local k/v into the pool — the slot
        row of the contiguous pool, or the allocated pages (quantized
        on the int8 path) of the paged pool. Pad-tail garbage is
        harmless: the per-slot causal mask hides positions > the
        current length, and each decode step overwrites position
        ``len`` right before attending it; padded PAGE slots point at
        the reserved trash page.

        DISAGGREGATED engines compile a COMPUTE-ONLY flavor on the
        PREFILL group instead: it returns the finished KV span (local
        rows, or paginated + int8-quantized page blocks) rather than
        writing the pool — the decode group owns the pool, and
        ``_kv_handoff`` ships + installs the span explicitly."""
        if self._prefill_jit is not None:
            return self._prefill_jit
        ad = self.adapter

        def local_run(params, buffers, ids, true_len):
            Lb = ids.shape[1]
            self.trace_counts["prefill"][Lb] = \
                self.trace_counts["prefill"].get(Lb, 0) + 1
            shape = (1, Lb, ad.kv_heads, ad.head_dim)
            local = [(jnp.zeros(shape, ad.dtype),
                      jnp.zeros(shape, ad.dtype), 0)
                     for _ in range(ad.num_layers)]
            with ad.model.bind_state(params, buffers):
                h, new_caches = ad.call(Tensor(ids), local)
                h_last = jax.lax.dynamic_slice_in_dim(
                    h._data, true_len - 1, 1, axis=1)
                logits = ad.head(Tensor(h_last))._data[0, -1]
            return logits, new_caches

        disagg = self.meshctx is not None \
            and self.meshctx.disaggregated

        if not self.paged:
            if disagg:
                def pure(params, buffers, ids, true_len):
                    logits, new_caches = local_run(params, buffers,
                                                   ids, true_len)
                    d = lambda c: getattr(c, "_data", c)
                    return (logits,
                            [d(c[0]) for c in new_caches],
                            [d(c[1]) for c in new_caches])

                psh, bsh, R, kv, _ = self._prog_shardings("prefill")
                self._prefill_jit = jax.jit(
                    pure, in_shardings=(psh, bsh, R, R),
                    out_shardings=(R, kv, kv))
                return self._prefill_jit

            def pure(params, buffers, ids, true_len, slot, ks, vs):
                logits, new_caches = local_run(params, buffers, ids,
                                               true_len)
                splice = lambda pool, c: jax.lax.dynamic_update_slice(
                    pool, getattr(c, "_data", c).astype(pool.dtype),
                    (slot, 0, 0, 0))
                ks = [splice(p, c[0]) for p, c in zip(ks, new_caches)]
                vs = [splice(p, c[1]) for p, c in zip(vs, new_caches)]
                return logits, ks, vs

            jit_kw = {}
            if self.meshctx is not None:
                psh, bsh, R, kv, _ = self._prog_shardings()
                jit_kw = dict(in_shardings=(psh, bsh, R, R, R, kv, kv),
                              out_shardings=(R, kv, kv))
            self._prefill_jit = jax.jit(pure,
                                        donate_argnums=self._donate(),
                                        **jit_kw)
            return self._prefill_jit

        from ..models._decode_cache import quantize_kv_page
        P = self.cache.page_size
        quant = self.kv_quant

        def paginate_fn(npg, pad):
            def paginate(c):
                a = getattr(c, "_data", c)
                if pad:
                    a = jnp.pad(a, ((0, 0), (0, pad), (0, 0), (0, 0)))
                return a.reshape(npg, P, *a.shape[2:])
            return paginate

        if disagg:
            def pure(params, buffers, ids, true_len):
                logits, new_caches = local_run(params, buffers, ids,
                                               true_len)
                npg = (ids.shape[1] + P - 1) // P
                paginate = paginate_fn(npg, npg * P - ids.shape[1])
                kb, vb, ksb, vsb = [], [], [], []
                for c in new_caches:
                    kpg, vpg = paginate(c[0]), paginate(c[1])
                    if quant:
                        # quantize on the PREFILL group: the handoff
                        # then ships int8 + scales, not model-dtype
                        kq, ksc = quantize_kv_page(kpg)
                        vq, vsc = quantize_kv_page(vpg)
                        kb.append(kq)
                        vb.append(vq)
                        ksb.append(ksc)
                        vsb.append(vsc)
                    else:
                        kb.append(kpg)
                        vb.append(vpg)
                return logits, kb, vb, ksb, vsb

            psh, bsh, R, kv, sc = self._prog_shardings("prefill")
            self._prefill_jit = jax.jit(
                pure, in_shardings=(psh, bsh, R, R),
                out_shardings=(R, kv, kv, sc, sc))
            return self._prefill_jit

        def pure(params, buffers, ids, true_len, page_ids, ks, vs,
                 kss, vss):
            logits, new_caches = local_run(params, buffers, ids,
                                           true_len)
            npg = page_ids.shape[0]
            paginate = paginate_fn(npg, npg * P - ids.shape[1])

            for i, c in enumerate(new_caches):
                kpg, vpg = paginate(c[0]), paginate(c[1])
                if quant:
                    kq, ksc = quantize_kv_page(kpg)
                    vq, vsc = quantize_kv_page(vpg)
                    ks[i] = ks[i].at[page_ids].set(kq)
                    vs[i] = vs[i].at[page_ids].set(vq)
                    kss[i] = kss[i].at[page_ids].set(ksc)
                    vss[i] = vss[i].at[page_ids].set(vsc)
                else:
                    ks[i] = ks[i].at[page_ids].set(
                        kpg.astype(ks[i].dtype))
                    vs[i] = vs[i].at[page_ids].set(
                        vpg.astype(vs[i].dtype))
            return logits, ks, vs, kss, vss

        jit_kw = {}
        if self.meshctx is not None:
            psh, bsh, R, kv, sc = self._prog_shardings()
            jit_kw = dict(
                in_shardings=(psh, bsh, R, R, R, kv, kv, sc, sc),
                out_shardings=(R, kv, kv, sc, sc))
        self._prefill_jit = jax.jit(
            pure, donate_argnums=self._donate_idx(5, 6, 7, 8),
            **jit_kw)
        return self._prefill_jit

    def _extend_fn(self):
        """Shared-prefix tail prefill ("extend"), one compile per tail
        bucket: the tail tokens run through the PAGED cache path at
        start position ``start``, attending over the already-shared
        prefix pages through the slot's page table and writing their
        own k/v through it (bucket-padding writes past the table fall
        into the trash page). Logits at the last REAL tail token.

        Disaggregation note: extends run on the DECODE group even when
        full prefills are offloaded — they attend over shared pages
        that already live in the decode-owned pool, and a prefix-hit
        tail is short by construction (docs/SERVING.md)."""
        if self._extend_jit is not None:
            return self._extend_jit
        ad = self.adapter
        jit_kw = {}
        if self.meshctx is not None:
            psh, bsh, R, kv, sc = self._prog_shardings()
            jit_kw = dict(
                in_shardings=(psh, bsh, R, R, R, R, kv, kv, sc, sc),
                out_shardings=(R, kv, kv, sc, sc))

        def pure(params, buffers, ids, start, true_tail, row, ks, vs,
                 kss, vss):
            Lb = ids.shape[1]
            self.trace_counts["extend"][Lb] = \
                self.trace_counts["extend"].get(Lb, 0) + 1
            caches = self._paged_caches(ks, vs, kss, vss,
                                        row[None, :], start)
            with ad.model.bind_state(params, buffers):
                h, new_caches = ad.call(Tensor(ids), caches)
                h_last = jax.lax.dynamic_slice_in_dim(
                    h._data, true_tail - 1, 1, axis=1)
                logits = ad.head(Tensor(h_last))._data[0, -1]
            return (logits,) + self._unpack_paged(new_caches)

        self._extend_jit = jax.jit(
            pure, donate_argnums=self._donate_idx(6, 7, 8, 9),
            **jit_kw)
        return self._extend_jit

    def _chunk_fn(self):
        """Chunked-prefill chunk program, one compile per chunk
        bucket: write chunk tokens ``start .. start + true_len - 1``
        into the slot's KV and attend over everything already written
        — positions beyond each query are masked to EXACT zero
        probability, so the outputs are bitwise what the monolithic
        prefill computed for the same positions (the greedy-identity
        argument, docs/SERVING.md "Chunked prefill"). Non-final
        chunks are exactly ``prefill_chunk`` tokens — their own
        bucket, zero padding; the final chunk's bucket padding is
        write-masked by ``true_len`` (contiguous) or trash-redirected
        (paged), the standard stale-tail story.

        Paged flavor: the paged EXTEND machinery verbatim (page-table
        writes at a mid-prompt start), counted under "chunk" so the
        compile-budget pins see chunk programs separately. Contiguous
        flavor: slice the slot row out of the pool, run the
        write-masked static-cache path at a scalar start, splice the
        row back."""
        if self._chunk_jit is not None:
            return self._chunk_jit
        ad = self.adapter

        if self.paged:
            jit_kw = {}
            if self.meshctx is not None:
                psh, bsh, R, kv, sc = self._prog_shardings()
                jit_kw = dict(
                    in_shardings=(psh, bsh, R, R, R, R, kv, kv,
                                  sc, sc),
                    out_shardings=(R, kv, kv, sc, sc))

            def pure(params, buffers, ids, start, true_len, row, ks,
                     vs, kss, vss):
                Lb = ids.shape[1]
                self.trace_counts["chunk"][Lb] = \
                    self.trace_counts["chunk"].get(Lb, 0) + 1
                caches = self._paged_caches(ks, vs, kss, vss,
                                            row[None, :], start)
                with ad.model.bind_state(params, buffers):
                    h, new_caches = ad.call(Tensor(ids), caches)
                    h_last = jax.lax.dynamic_slice_in_dim(
                        h._data, true_len - 1, 1, axis=1)
                    logits = ad.head(Tensor(h_last))._data[0, -1]
                return (logits,) + self._unpack_paged(new_caches)

            self._chunk_jit = jax.jit(
                pure, donate_argnums=self._donate_idx(6, 7, 8, 9),
                **jit_kw)
            return self._chunk_jit

        jit_kw = {}
        if self.meshctx is not None:
            psh, bsh, R, kv, _ = self._prog_shardings()
            jit_kw = dict(
                in_shardings=(psh, bsh, R, R, R, R, kv, kv),
                out_shardings=(R, kv, kv))

        def pure(params, buffers, ids, start, true_len, slot, ks, vs):
            Lb = ids.shape[1]
            self.trace_counts["chunk"][Lb] = \
                self.trace_counts["chunk"].get(Lb, 0) + 1
            rows = lambda pool: jax.lax.dynamic_slice(
                pool, (slot, 0, 0, 0), (1,) + pool.shape[1:])
            wl = jnp.reshape(jnp.asarray(true_len, jnp.int32), (1,))
            caches = [(rows(k), rows(v), start, wl)
                      for k, v in zip(ks, vs)]
            with ad.model.bind_state(params, buffers):
                h, new_caches = ad.call(Tensor(ids), caches)
                h_last = jax.lax.dynamic_slice_in_dim(
                    h._data, true_len - 1, 1, axis=1)
                logits = ad.head(Tensor(h_last))._data[0, -1]
            splice = lambda pool, c: jax.lax.dynamic_update_slice(
                pool, getattr(c, "_data", c).astype(pool.dtype),
                (slot, 0, 0, 0))
            ks = [splice(p, c[0]) for p, c in zip(ks, new_caches)]
            vs = [splice(p, c[1]) for p, c in zip(vs, new_caches)]
            return logits, ks, vs

        self._chunk_jit = jax.jit(
            pure, donate_argnums=self._donate_idx(6, 7), **jit_kw)
        return self._chunk_jit

    def _chunk_local_fn(self):
        """Disaggregated chunk program on the PREFILL group: advance
        one chunk through the request's local [1, max_len] contiguous
        buffers (write-masked past ``true_len``); the final span
        ships via ``_kv_handoff`` (contiguous) or the paged finalize
        program. One compile per chunk bucket — the buffers are
        always full-length, so the key space is the ids bucket
        alone."""
        if self._chunk_local_jit is not None:
            return self._chunk_local_jit
        ad = self.adapter

        def pure(params, buffers, ids, start, true_len, kb, vb):
            Lb = ids.shape[1]
            key = ("local", Lb)
            self.trace_counts["chunk"][key] = \
                self.trace_counts["chunk"].get(key, 0) + 1
            wl = jnp.reshape(jnp.asarray(true_len, jnp.int32), (1,))
            caches = [(k, v, start, wl) for k, v in zip(kb, vb)]
            with ad.model.bind_state(params, buffers):
                h, new_caches = ad.call(Tensor(ids), caches)
                h_last = jax.lax.dynamic_slice_in_dim(
                    h._data, true_len - 1, 1, axis=1)
                logits = ad.head(Tensor(h_last))._data[0, -1]
            kb2 = [getattr(c[0], "_data", c[0]) for c in new_caches]
            vb2 = [getattr(c[1], "_data", c[1]) for c in new_caches]
            return logits, kb2, vb2

        psh, bsh, R, kv, _ = self._prog_shardings("prefill")
        self._chunk_local_jit = jax.jit(
            pure, in_shardings=(psh, bsh, R, R, R, kv, kv),
            out_shardings=(R, kv, kv),
            donate_argnums=self._donate_idx(5, 6))
        return self._chunk_local_jit

    def _chunk_fin_fn(self, npg: int):
        """Paged disaggregated finalize program, one compile per page
        count: paginate the accumulated local buffers into the
        request's ``npg`` page blocks (int8-quantized here on the
        quantized path — every page is complete by now, so per-page
        scales are exact) for the standard handoff install."""
        if self._chunk_fin_jit is None:
            self._chunk_fin_jit = {}
        fn = self._chunk_fin_jit.get(npg)
        if fn is not None:
            return fn
        from ..models._decode_cache import quantize_kv_page
        P = self.cache.page_size
        quant = self.kv_quant
        m = self.meshctx
        L = self.adapter.num_layers
        kv = [m.kv_sharding("prefill")] * L
        sc = [m.scale_sharding("prefill")] * L if quant else []

        def pure(kb, vb):
            key = ("fin", npg)
            self.trace_counts["chunk"][key] = \
                self.trace_counts["chunk"].get(key, 0) + 1
            kpg, vpg, kspg, vspg = [], [], [], []
            for k, v in zip(kb, vb):
                kp = k[:, :npg * P].reshape(npg, P, *k.shape[2:])
                vp = v[:, :npg * P].reshape(npg, P, *v.shape[2:])
                if quant:
                    kq, ksc = quantize_kv_page(kp)
                    vq, vsc = quantize_kv_page(vp)
                    kpg.append(kq)
                    vpg.append(vq)
                    kspg.append(ksc)
                    vspg.append(vsc)
                else:
                    kpg.append(kp)
                    vpg.append(vp)
            return kpg, vpg, kspg, vspg

        fn = jax.jit(pure, in_shardings=(kv, kv),
                     out_shardings=(kv, kv, sc, sc))
        self._chunk_fin_jit[npg] = fn
        return fn

    def _install_fn(self, key):
        """Decode-group INSTALL program for one handed-off KV span
        (disaggregated engines only), compiled once per block shape:
        paged — scatter the shipped page blocks (int8 + scales on the
        quantized path) into the pool at the claimed page ids;
        contiguous — splice the shipped rows into the slot row. The
        shape key space is the prefill bucket set, so installs stay
        inside the same O(log max_len) compile budget as prefills."""
        if self._install_jit is None:
            self._install_jit = {}
        fn = self._install_jit.get(key)
        if fn is not None:
            return fn
        m = self.meshctx
        L = self.adapter.num_layers
        R = m.repl()
        kv = [m.kv_sharding()] * L
        sc = [m.scale_sharding()] * L \
            if (self.paged and self.kv_quant) else []

        def count():
            self.trace_counts["install"][key] = \
                self.trace_counts["install"].get(key, 0) + 1

        if self.paged:
            def pure(page_ids, kb, vb, ksb, vsb, ks, vs, kss, vss):
                count()
                ks = [p.at[page_ids].set(b.astype(p.dtype))
                      for p, b in zip(ks, kb)]
                vs = [p.at[page_ids].set(b.astype(p.dtype))
                      for p, b in zip(vs, vb)]
                kss = [p.at[page_ids].set(b)
                       for p, b in zip(kss, ksb)]
                vss = [p.at[page_ids].set(b)
                       for p, b in zip(vss, vsb)]
                return ks, vs, kss, vss

            fn = jax.jit(
                pure,
                in_shardings=(R, kv, kv, sc, sc, kv, kv, sc, sc),
                out_shardings=(kv, kv, sc, sc),
                donate_argnums=self._donate_idx(5, 6, 7, 8))
        else:
            def pure(slot, kb, vb, ks, vs):
                count()
                splice = lambda pool, b: jax.lax.dynamic_update_slice(
                    pool, b.astype(pool.dtype), (slot, 0, 0, 0))
                return ([splice(p, b) for p, b in zip(ks, kb)],
                        [splice(p, b) for p, b in zip(vs, vb)])

            fn = jax.jit(pure,
                         in_shardings=(R, kv, kv, kv, kv),
                         out_shardings=(kv, kv),
                         donate_argnums=self._donate_idx(3, 4))
        self._install_jit[key] = fn
        return fn

    def _kv_handoff(self, req, slot, blocks, page_ids=None,
                    cancel_check: bool = False) -> None:
        """Disaggregated prefill -> decode KV handoff: ship a finished
        prefill's KV span from the prefill group to the decode group
        (explicit cross-group ``jax.device_put``) and install it into
        the decode-owned pool. The ``serving.kv.handoff`` fault point
        fires BETWEEN compute and install — a raise here (injected
        fault, client disconnect observed mid-handoff) routes through
        the caller's abort path, so a half-handed-off request unwinds
        on BOTH groups: the staged span is dropped with this frame and
        the decode pool's page claims return via abort_sequence. The
        staging ledger `_staged_handoffs` is audited empty at quiesce
        (cross-group no-leak law, resilience/invariants.py)."""
        m = self.meshctx
        rid = req.rid if req is not None else -1
        # staged BEFORE the kill point; popped on successful install,
        # or by the caller's ABORT path on any raise below — the same
        # path that returns the decode-side page claims, so a
        # regression that forgets either unwind half trips the
        # cross-group leak audit (a finally here would clear it
        # unconditionally and make that audit vacuous)
        self._staged_handoffs[rid] = slot
        maybe_fail("serving.kv.handoff", slot=slot, rid=rid)
        if cancel_check and req is not None \
                and self._cancel_requested(req):
            # the client vanished while its KV sat staged on the
            # prefill group: don't ship or install a span nobody
            # will decode — the abort path frees the page claims
            raise RequestCancelled(
                req.rid, "client disconnected mid-KV-handoff")
        if self.kv_transport is not None:
            # cross-host hop: the blocks leave as bytes on a real
            # socket and come back digest-verified (kv_wire.py) —
            # what lands on the decode group below is what the wire
            # delivered, not the local arrays. A KVWireError past the
            # transport's retry budget raises HERE, inside the staged
            # window, so the caller's abort path unwinds both halves.
            parts = [list(p) for p in blocks]
            flat = [np.asarray(a) for part in parts for a in part]
            with span("serving.kv_wire", slot=slot, request_id=rid,
                      arrays=len(flat)):
                flat = self.kv_transport.ship(rid, flat)
            it = iter(flat)
            blocks = tuple([next(it) for _ in part]
                           for part in parts)
        L = self.adapter.num_layers
        dec_kv = [m.kv_sharding()] * L
        c = self.cache
        with span("serving.kv_handoff", slot=slot, request_id=rid):
            if self.paged:
                kb, vb, ksb, vsb = blocks
                kb = jax.device_put(list(kb), dec_kv)
                vb = jax.device_put(list(vb), dec_kv)
                if self.kv_quant:
                    dec_sc = [m.scale_sharding()] * L
                    ksb = jax.device_put(list(ksb), dec_sc)
                    vsb = jax.device_put(list(vsb), dec_sc)
                out = self._install_fn(
                    ("paged", int(page_ids.shape[0])))(
                    page_ids, kb, vb, list(ksb), list(vsb),
                    c.ks, c.vs, c.kss, c.vss)
                c.ks, c.vs = list(out[0]), list(out[1])
                c.kss, c.vss = list(out[2]), list(out[3])
            else:
                kb, vb = blocks
                kb = jax.device_put(list(kb), dec_kv)
                vb = jax.device_put(list(vb), dec_kv)
                ks, vs = self._install_fn(
                    ("contig", int(kb[0].shape[1])))(
                    np.int32(slot), kb, vb, c.ks, c.vs)
                c.ks, c.vs = list(ks), list(vs)
        self._staged_handoffs.pop(rid, None)

    def _stage_promotions(self, req, slot: int) -> None:
        """Install this request's planned tier promotions onto their
        fresh device pages BEFORE the extend program reads them —
        the host-tier mirror of :meth:`_kv_handoff`'s staged
        install/abort contract. Staged in ``_staged_promotions``
        before the ``serving.kv.promote`` kill point; popped on
        successful commit, or unwound HERE via ``abort_sequence`` on
        any raise (the caller's handler re-aborting is a safe no-op:
        the plan is already popped). A fault therefore returns the
        promotion dst pages AND the tier pins in the same unwind, so
        neither tier leaks."""
        plan = self.cache._plans.get(req.rid)
        if plan is None or not plan["promote"]:
            return
        rid = req.rid
        c = self.cache
        self._staged_promotions[rid] = slot
        self.metrics.on_promotion_start(rid)
        t0 = self.metrics.now()
        try:
            maybe_fail("serving.kv.promote", slot=slot, rid=rid,
                       pages=len(plan["promote"]))
            with span("serving.kv_promote", slot=slot,
                      request_id=rid, pages=len(plan["promote"])):
                work = c.begin_promotions(req)
                # async H2D first: every payload is on its way to the
                # device before the first install dispatch
                shipped = []
                for node, dst, payload, label in work:
                    kb = jax.device_put(list(payload["k"]))
                    vb = jax.device_put(list(payload["v"]))
                    ksb = jax.device_put(list(payload["ks"])) \
                        if self.kv_quant else []
                    vsb = jax.device_put(list(payload["vs"])) \
                        if self.kv_quant else []
                    shipped.append((dst, kb, vb, ksb, vsb))
                fn = self._promote_fn()
                for dst, kb, vb, ksb, vsb in shipped:
                    out = fn(np.int32(dst), kb, vb, ksb, vsb,
                             c.ks, c.vs, c.kss, c.vss)
                    c.ks, c.vs = list(out[0]), list(out[1])
                    c.kss, c.vss = list(out[2]), list(out[3])
                c.commit_promotions(req, work)
        except BaseException:
            self._staged_promotions.pop(rid, None)
            c.abort_sequence(slot, req)
            raise
        self._staged_promotions.pop(rid, None)
        self.metrics.on_promotion(rid, self.metrics.now() - t0)

    def _copy_fn(self):
        """COW page copy (compiled once): pool[dst] <- pool[src] for
        every layer's k/v (+scale) pool."""
        if self._copy_jit is not None:
            return self._copy_jit
        jit_kw = {}
        if self.meshctx is not None:
            _, _, R, kv, sc = self._prog_shardings()
            jit_kw = dict(in_shardings=(R, R, kv, kv, sc, sc),
                          out_shardings=(kv, kv, sc, sc))

        def pure(src, dst, ks, vs, kss, vss):
            self.trace_counts["copy"] += 1
            cp = lambda pool: pool.at[dst].set(pool[src])
            return ([cp(p) for p in ks], [cp(p) for p in vs],
                    [cp(p) for p in kss], [cp(p) for p in vss])

        self._copy_jit = jax.jit(
            pure, donate_argnums=self._donate_idx(2, 3, 4, 5),
            **jit_kw)
        return self._copy_jit

    def _promote_fn(self):
        """Tier promotion install (compiled once): scatter ONE host-
        tier page's k/v blocks (+int8 scales) into a fresh device page
        across every layer pool. One page per call keeps the program
        shape static — promotion cost is page-count many dispatches of
        the same compiled program, never a recompile."""
        if self._promote_jit is not None:
            return self._promote_jit

        def pure(dst, kb, vb, ksb, vsb, ks, vs, kss, vss):
            self.trace_counts["promote"] += 1
            put = lambda pool, b: pool.at[dst].set(
                b.astype(pool.dtype))
            return ([put(p, b) for p, b in zip(ks, kb)],
                    [put(p, b) for p, b in zip(vs, vb)],
                    [put(p, b) for p, b in zip(kss, ksb)],
                    [put(p, b) for p, b in zip(vss, vsb)])

        self._promote_jit = jax.jit(
            pure, donate_argnums=self._donate_idx(5, 6, 7, 8))
        return self._promote_jit

    def _decode_fn(self):
        """THE decode-step program (compiled once): every occupied slot
        advances one token at its own position; the active-slot mask
        pins inactive lanes to position 0 and zeroes their logits so
        they stay numerically inert whatever garbage their row holds.
        Paged flavor: same contract, but k/v flow through the page
        tables (inactive rows pinned to the trash page) — paging adds
        ZERO decode compiles beyond this one program.

        Mesh flavor: the SAME program jitted under the decode group's
        mesh with explicit in/out shardings — params by the family's
        tp_param_spec rules, pools split on kv_heads, token/position/
        mask blocks replicated. Still exactly ONE compile per mesh
        shape, and bitwise token-identical to the single-chip program
        (output-dim-only sharding: no float sum is re-associated)."""
        if self._decode_jit is not None:
            return self._decode_jit
        ad = self.adapter
        jit_kw = {}
        if self.meshctx is not None:
            psh, bsh, R, kv, sc = self._prog_shardings()
            if self.paged:
                jit_kw = dict(
                    in_shardings=(psh, bsh, R, R, R, R, kv, kv, sc, sc),
                    out_shardings=(R, kv, kv, sc, sc))
            else:
                jit_kw = dict(in_shardings=(psh, bsh, R, R, R, kv, kv),
                              out_shardings=(R, kv, kv))

        if self.paged:
            def pure(params, buffers, toks, pos, active, tables, ks,
                     vs, kss, vss):
                self.trace_counts["decode"] += 1
                pos_eff = jnp.where(active, pos, 0).astype(jnp.int32)
                tab_eff = jnp.where(active[:, None], tables, 0)
                caches = self._paged_caches(ks, vs, kss, vss,
                                            tab_eff, pos_eff)
                with ad.model.bind_state(params, buffers):
                    h, new_caches = ad.call(Tensor(toks), caches)
                    logits = ad.head(h[:, -1:])._data[:, -1]
                logits = jnp.where(active[:, None], logits, 0.0)
                return (logits,) + self._unpack_paged(new_caches)

            self._decode_jit = jax.jit(
                pure, donate_argnums=self._donate_idx(6, 7, 8, 9),
                **jit_kw)
            return self._decode_jit

        masked = self.prefill_chunk is not None

        def pure(params, buffers, toks, pos, active, ks, vs):
            self.trace_counts["decode"] += 1
            pos_eff = jnp.where(active, pos, 0).astype(jnp.int32)
            if masked:
                # chunked engines write-mask INACTIVE lanes: the plain
                # flavor writes every lane's k/v at position 0, which
                # was harmless while every admission rewrote the whole
                # row — but a PREFILLING slot's row must survive the
                # decode steps interleaved between its chunks. Active
                # lanes' writes/attends are bitwise unchanged (the
                # wlen scatter lands the same k/v at the same
                # positions), so greedy outputs stay identical.
                wl = jnp.where(active, 1, 0).astype(jnp.int32)
                caches = [(k, v, pos_eff, wl) for k, v in zip(ks, vs)]
            else:
                caches = [(k, v, pos_eff) for k, v in zip(ks, vs)]
            with ad.model.bind_state(params, buffers):
                h, new_caches = ad.call(Tensor(toks), caches)
                logits = ad.head(h[:, -1:])._data[:, -1]
            logits = jnp.where(active[:, None], logits, 0.0)
            ks2 = [getattr(c[0], "_data", c[0]) for c in new_caches]
            vs2 = [getattr(c[1], "_data", c[1]) for c in new_caches]
            return logits, ks2, vs2

        self._decode_jit = jax.jit(pure, donate_argnums=self._donate(),
                                   **jit_kw)
        return self._decode_jit

    def _verify_fn(self):
        """THE speculative verify program (compiled once per engine):
        every occupied slot advances up to k tokens at its own
        position. The input block per row is [last emitted token,
        draft_1 .. draft_{k-1}] (padded past the row's per-row length
        ``wlen``); the cache write of token j is masked to j < wlen
        (models/_decode_cache wlen contract), the causal mask already
        scopes position j to everything <= pos + j, and the program
        returns, for every row: the k position logits, the k greedy
        (argmax) tokens, and the ACCEPTED LENGTH — 1 (the k=1 base
        token, always emitted) plus the leading run of draft tokens
        that equal the greedy token predicted one position earlier.
        That acceptance rule is exactly greedy sequential decode run k
        steps ahead, which is the token-identity proof: an accepted
        token had the same logits inputs (same cache state, same
        causal scope) as its sequential counterpart. k=1 fallback rows
        are just wlen=1 rows of the SAME program — zero extra
        compiles, trace-count asserted."""
        if self._verify_jit is not None:
            return self._verify_jit
        ad = self.adapter
        jit_kw = {}
        if self.meshctx is not None:
            psh, bsh, R, kv, sc = self._prog_shardings()
            if self.paged:
                jit_kw = dict(
                    in_shardings=(psh, bsh, R, R, R, R, R,
                                  kv, kv, sc, sc),
                    out_shardings=(R, R, R, kv, kv, sc, sc))
            else:
                jit_kw = dict(
                    in_shardings=(psh, bsh, R, R, R, R, kv, kv),
                    out_shardings=(R, R, R, kv, kv))

        def accept(toks, logits, wl_eff, active):
            K = toks.shape[1]
            g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K]
            if K > 1:
                # draft j (input position j, 1-based) is accepted iff
                # it equals the greedy prediction at position j-1 AND
                # is a real draft token (j < wlen); the leading-run
                # length is a cumprod sum
                m = (toks[:, 1:].astype(jnp.int32) == g[:, :-1]) \
                    & (jnp.arange(1, K, dtype=jnp.int32)[None, :]
                       < wl_eff[:, None])
                acc = 1 + jnp.sum(jnp.cumprod(m.astype(jnp.int32),
                                              axis=1), axis=1)
            else:
                acc = jnp.ones(toks.shape[0], jnp.int32)
            acc = jnp.where(active, acc, 0).astype(jnp.int32)
            return g, acc

        if self.paged:
            def pure(params, buffers, toks, pos, active, wlen, tables,
                     ks, vs, kss, vss):
                self.trace_counts["verify"] += 1
                pos_eff = jnp.where(active, pos, 0).astype(jnp.int32)
                wl_eff = jnp.where(active, wlen, 0).astype(jnp.int32)
                tab_eff = jnp.where(active[:, None], tables, 0)
                caches = self._paged_caches(ks, vs, kss, vss,
                                            tab_eff, pos_eff,
                                            wlen=wl_eff)
                with ad.model.bind_state(params, buffers):
                    h, new_caches = ad.call(Tensor(toks), caches)
                    logits = ad.head(h)._data        # [B, K, vocab]
                logits = jnp.where(active[:, None, None], logits, 0.0)
                g, acc = accept(toks, logits, wl_eff, active)
                return (logits, g, acc) \
                    + self._unpack_paged(new_caches)

            self._verify_jit = jax.jit(
                pure, donate_argnums=self._donate_idx(7, 8, 9, 10),
                **jit_kw)
            return self._verify_jit

        def pure(params, buffers, toks, pos, active, wlen, ks, vs):
            self.trace_counts["verify"] += 1
            pos_eff = jnp.where(active, pos, 0).astype(jnp.int32)
            wl_eff = jnp.where(active, wlen, 0).astype(jnp.int32)
            caches = [(k, v, pos_eff, wl_eff)
                      for k, v in zip(ks, vs)]
            with ad.model.bind_state(params, buffers):
                h, new_caches = ad.call(Tensor(toks), caches)
                logits = ad.head(h)._data            # [B, K, vocab]
            logits = jnp.where(active[:, None, None], logits, 0.0)
            g, acc = accept(toks, logits, wl_eff, active)
            ks2 = [getattr(c[0], "_data", c[0]) for c in new_caches]
            vs2 = [getattr(c[1], "_data", c[1]) for c in new_caches]
            return logits, g, acc, ks2, vs2

        self._verify_jit = jax.jit(
            pure, donate_argnums=self._donate_idx(6, 7), **jit_kw)
        return self._verify_jit

    @staticmethod
    def _donate():
        """Donation enable flag + the contiguous programs' pool
        argument indices (args 5/6): non-empty means the jit update is
        in-place on device. CPU ignores donation and warns, so skip
        it there. Paged programs derive their own indices from this
        flag via ``_donate_idx`` (tests monkeypatch ``_donate`` to
        simulate the TPU donated-pool failure mode)."""
        return () if jax.default_backend() == "cpu" else (5, 6)

    def _donate_idx(self, *idx):
        return idx if self._donate() else ()
