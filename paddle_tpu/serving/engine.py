"""Continuous-batching serving engine over the static KV-cache decode
path.

ONE compiled decode-step program (fixed ``[max_slots, 1]`` token block,
per-slot positions, active-slot mask) serves any mix of in-flight
requests; prefill compiles once per power-of-2 length bucket. Compare
``benchmarks/bench_llama_decode.py``'s synchronized path, where every
sequence in a batch starts and stops together and slots idle while the
longest request finishes — here freed slots are refilled from the
queue at every iteration (Orca-style iteration-level scheduling), so
ragged traffic keeps the batch dense.

Synchronous API by design (the repo's serving story is one compiled
program per step, driven by a host loop):

    engine = ServingEngine(model, max_slots=8, max_len=256, eos_id=2)
    r1 = engine.submit(prompt, max_new_tokens=32)
    while engine.has_work():
        finished = engine.step()
    print(r1.output_ids, engine.metrics.summary())
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..observability import default_recorder, default_registry, span
from .metrics import EngineMetrics
from .sampling import SamplingParams, sample_token
from .scheduler import FIFOScheduler, Request, bucket_for
from .slot_cache import SlotKVCache

__all__ = ["ServingEngine"]


class _ModelAdapter:
    """Uniform view over the causal LMs that expose the static-cache
    path (models/llama.py natively; models/gpt.py via its cache-aware
    forward): a backbone callable taking (ids, caches), a logits head,
    and the cache geometry."""

    def __init__(self, model):
        self.model = model
        if hasattr(model, "llama"):          # LlamaForCausalLM
            cfg = model.config
            backbone = model.llama
            self.call = lambda ids, caches: backbone(ids, None, caches)
            self.head = model._head
            self.num_layers = len(backbone.layers)
            self.head_dim = cfg.head_dim
            attn0 = backbone.layers[0].self_attn
            kp = attn0.k_proj       # Linear (weight) or Int8Linear (wq)
            kw = kp.weight if hasattr(kp, "weight") else kp.wq
            self.kv_heads = kw.shape[-1] // cfg.head_dim
            self.max_positions = cfg.max_position_embeddings
            self.dtype = backbone.embed_tokens.weight._data.dtype
        elif hasattr(model, "gpt"):          # GPTForCausalLM
            cfg = model.cfg
            backbone = model.gpt
            self.call = lambda ids, caches: backbone(ids, caches=caches)
            self.head = model._head
            self.num_layers = len(backbone.blocks)
            self.head_dim = cfg.head_dim
            qw = backbone.blocks[0].qkv.weight
            self.kv_heads = qw.shape[-1] // (3 * cfg.head_dim)
            self.max_positions = cfg.max_seq_len
            self.dtype = backbone.wte.weight._data.dtype
        else:
            raise TypeError(
                f"{type(model).__name__} exposes no static-cache decode "
                "path the serving engine can drive (expected a .llama "
                "or .gpt backbone with a (k, v, pos) cache forward)")


class ServingEngine:
    """Slot-based continuous-batching engine (see module docstring)."""

    def __init__(self, model, max_slots: int = 8,
                 max_len: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 min_bucket: int = 16,
                 time_fn: Callable[[], float] = time.perf_counter,
                 registry=None, flight_recorder=None):
        self.adapter = _ModelAdapter(model)
        model.eval()
        self.max_slots = int(max_slots)
        self.max_len = int(max_len or self.adapter.max_positions)
        if self.max_len > self.adapter.max_positions:
            raise ValueError(
                f"max_len {self.max_len} exceeds the model's position "
                f"range {self.adapter.max_positions}")
        self.eos_id = eos_id
        self.min_bucket = min(int(min_bucket), self.max_len)
        self.cache = SlotKVCache(
            self.adapter.num_layers, self.max_slots, self.max_len,
            self.adapter.kv_heads, self.adapter.head_dim,
            self.adapter.dtype)
        self.scheduler = FIFOScheduler()
        self.registry = registry if registry is not None \
            else default_registry()
        # `is None`, not truthiness: an EMPTY FlightRecorder is falsy
        # (it has __len__), and `or` would silently swap it for the
        # global one
        self.recorder = flight_recorder if flight_recorder is not None \
            else default_recorder()
        self.metrics = EngineMetrics(self.max_slots, time_fn,
                                     registry=self.registry)
        self._params, self._buffers = model.raw_state()
        self._decode_jit = None
        self._prefill_jit = None
        self._next_rid = 0
        self._step_idx = 0
        self._poisoned: Optional[str] = None
        # python-side-effect counters bumped at TRACE time: the compile-
        # count contract (1 decode + O(log max_len) prefill buckets) is
        # asserted against these in tests
        self.trace_counts = {"decode": 0, "prefill": {}}
        reg = self.registry
        self._m_queue_depth = reg.gauge(
            "ptpu_serving_queue_depth", "requests waiting for a slot")
        self._m_active = reg.gauge(
            "ptpu_serving_active_slots", "slots decoding this step")
        self._m_step = reg.histogram(
            "ptpu_serving_step_seconds",
            "wall time of one engine iteration (engine clock)")
        self._m_prefill = reg.counter(
            "ptpu_serving_prefills_total", "prefill program runs",
            labels=("bucket",))
        self._m_evict = reg.counter(
            "ptpu_serving_evictions_total", "slots freed",
            labels=("reason",))

    # -- public API ----------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 16,
               sampling: Optional[SamplingParams] = None) -> Request:
        """Queue one request; returns its handle (tokens appear on it
        as steps run)."""
        if self._poisoned:
            raise RuntimeError(
                f"ServingEngine is unrecoverable (donated cache pools "
                f"invalidated by a failed step: {self._poisoned}); "
                f"build a fresh engine.")
        ids = np.asarray(getattr(prompt_ids, "numpy", lambda: prompt_ids)()
                         ).astype(np.int64)
        if ids.ndim == 2 and ids.shape[0] == 1:   # [1, T] batch-of-one
            ids = ids[0]
        if ids.ndim != 1:
            # a [B, T] batch must not silently flatten into ONE merged
            # request — submit() takes one sequence per call
            raise ValueError(
                f"submit() takes a single prompt sequence; got shape "
                f"{ids.shape}. Call submit() once per request.")
        if ids.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if ids.size + max_new_tokens - 1 > self.max_len:
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens "
                f"({max_new_tokens}) - 1 exceeds max_len {self.max_len}")
        sampling = sampling or SamplingParams()
        sampling.validate()
        req = Request(rid=self._next_rid, prompt=ids,
                      max_new_tokens=int(max_new_tokens),
                      sampling=sampling)
        req._rng = np.random.RandomState(
            sampling.seed if sampling.seed is not None
            else 0x5EED + req.rid)
        self._next_rid += 1
        self.scheduler.add(req)
        self.metrics.on_submit(req.rid)
        self._m_queue_depth.set(self.scheduler.depth)
        return req

    def has_work(self) -> bool:
        return self.scheduler.has_pending() or \
            bool(self.cache.active_slots())

    def step(self) -> List[Request]:
        """One engine iteration: admit into free slots (bucketed
        prefill), then one decode step over every occupied slot, then
        evict finished sequences. Returns requests finished this step.

        Every step appends a flight-recorder record (latency, slot
        occupancy, queue depth, admissions/evictions, compile events);
        if the step raises, the recorder ring dumps to disk before the
        exception propagates — the post-mortem for a dead serving
        loop."""
        if self._poisoned:
            raise RuntimeError(
                f"ServingEngine is unrecoverable: a previous step "
                f"failed after its cache pools were donated (device "
                f"buffers invalidated) — {self._poisoned}. Build a "
                f"fresh engine; the flight-recorder dump has the "
                f"post-mortem.")
        t0 = self.metrics.now()
        step_idx = self._step_idx
        self._step_idx += 1
        tc0 = (self.trace_counts["decode"],
               sum(self.trace_counts["prefill"].values()))
        try:
            with span("serving.step", step=step_idx) as sp:
                finished, admitted, n_active = self._step_inner()
                sp.set_attr("active_slots", n_active)
        except Exception as e:
            if self._donate():
                # the jit call may have CONSUMED the donated pools
                # before failing: ks/vs can reference deleted device
                # buffers, and any later step would die confusingly —
                # refuse further use instead
                self._poisoned = f"step #{step_idx}: " \
                                 f"{type(e).__name__}: {e}"
            try:
                self.recorder.record(
                    "serving.step_error", step=step_idx,
                    error=f"{type(e).__name__}: {e}")
                path = self.recorder.dump(
                    reason=f"ServingEngine.step #{step_idx} raised "
                           f"{type(e).__name__}: {e}",
                    registry=self.registry)
                import sys
                print(f"[serving] flight recorder dumped to {path}",
                      file=sys.stderr)
            except Exception:
                pass               # never mask the original failure
            raise
        dt = self.metrics.now() - t0
        depth = self.scheduler.depth
        self._m_step.observe(dt)
        self._m_queue_depth.set(depth)
        self._m_active.set(n_active)
        self.recorder.record(
            "serving.step", step=step_idx, step_latency_s=dt,
            active_slots=n_active, queue_depth=depth,
            admitted=admitted,
            evicted=[(r.rid, r.finish_reason) for r in finished],
            compiles_decode=self.trace_counts["decode"] - tc0[0],
            compiles_prefill=(
                sum(self.trace_counts["prefill"].values()) - tc0[1]))
        return finished

    def _step_inner(self):
        finished: List[Request] = []
        admitted: List[int] = []
        # re-snapshot the weights so checkpoint loads / quantization on
        # the live model object take effect next step (same pytree
        # structure -> no retrace; the arrays are just jit arguments)
        self._params, self._buffers = self.adapter.model.raw_state()
        # 1) admission — freed slots refill BEFORE the decode so a new
        # request's first decode token rides this very step
        for slot, req in self.scheduler.admissions(
                self.cache.free_slots()):
            self._prefill(slot, req)
            admitted.append(req.rid)
            if req.finished:
                self._evict(slot, req, finished)
        # 2) one decode step over all occupied slots
        active = self.cache.active_slots()
        if active:
            toks = np.zeros((self.max_slots, 1), np.int64)
            pos = np.zeros((self.max_slots,), np.int32)
            mask = np.zeros((self.max_slots,), bool)
            for s in active:
                req = self.cache.slots[s]
                toks[s, 0] = req.out_tokens[-1]
                pos[s] = req.next_pos
                mask[s] = True
            with span("serving.decode", batch=len(active),
                      request_ids=[self.cache.slots[s].rid
                                   for s in active]):
                logits, ks, vs = self._decode_fn()(
                    self._params, self._buffers, toks, pos, mask,
                    self.cache.ks, self.cache.vs)
                self.cache.ks, self.cache.vs = list(ks), list(vs)
                logits = np.asarray(jax.device_get(logits))
            for s in active:
                req = self.cache.slots[s]
                tok = sample_token(logits[s], req.sampling, req._rng)
                req.out_tokens.append(tok)
                self.metrics.on_token(req.rid)
                if self._is_finished(req, tok):
                    self._evict(s, req, finished)
        self.metrics.on_step(len(active))
        return finished, admitted, len(active)

    def _evict(self, slot: int, req: Request,
               finished: List[Request]) -> None:
        self.cache.release(slot)
        req.slot = None
        finished.append(req)
        self._m_evict.labels(reason=req.finish_reason or "unknown").inc()
        self.metrics.on_finished(req.rid)

    def run(self, max_steps: Optional[int] = None) -> List[Request]:
        """Drive step() until the queue and every slot drain."""
        done: List[Request] = []
        steps = 0
        while self.has_work():
            done.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return done

    # -- internals -----------------------------------------------------
    def _is_finished(self, req: Request, tok: int) -> bool:
        if self.eos_id is not None and tok == self.eos_id:
            req.finished, req.finish_reason = True, "eos"
        elif len(req.out_tokens) >= req.max_new_tokens:
            req.finished, req.finish_reason = True, "length"
        return req.finished

    def _prefill(self, slot: int, req: Request) -> None:
        """Run the bucketed prefill program for one request, write its
        k/v into the slot row, and sample its first token (TTFT)."""
        bucket = bucket_for(req.prompt_len, self.min_bucket,
                            self.max_len)
        self.metrics.on_first_prefill(req.rid)   # queue wait ends here
        self._m_prefill.labels(bucket=bucket).inc()
        with span("serving.prefill", request_id=req.rid, slot=slot,
                  bucket=bucket, prompt_len=req.prompt_len):
            ids = np.zeros((1, bucket), np.int64)
            ids[0, :req.prompt_len] = req.prompt
            logits, ks, vs = self._prefill_fn()(
                self._params, self._buffers, ids,
                np.int32(req.prompt_len), np.int32(slot),
                self.cache.ks, self.cache.vs)
            self.cache.ks, self.cache.vs = list(ks), list(vs)
        self.cache.assign(slot, req)
        req.slot = slot
        tok = sample_token(np.asarray(jax.device_get(logits)),
                           req.sampling, req._rng)
        req.out_tokens.append(tok)
        self.metrics.on_token(req.rid)
        self._is_finished(req, tok)

    def _prefill_fn(self):
        """Prefill program, one compile per bucket length: run the
        prompt through a local [1, bucket] static cache, take the
        logits at the LAST REAL token (the bucket tail is padding), and
        splice the local k/v into the slot row of the donated pool.
        Pad-tail garbage in the row is harmless: the per-slot causal
        mask hides positions > the current length, and each decode step
        overwrites position ``len`` right before attending it."""
        if self._prefill_jit is not None:
            return self._prefill_jit
        ad = self.adapter

        def pure(params, buffers, ids, true_len, slot, ks, vs):
            Lb = ids.shape[1]
            self.trace_counts["prefill"][Lb] = \
                self.trace_counts["prefill"].get(Lb, 0) + 1
            shape = (1, Lb, ad.kv_heads, ad.head_dim)
            local = [(jnp.zeros(shape, ad.dtype),
                      jnp.zeros(shape, ad.dtype), 0)
                     for _ in range(ad.num_layers)]
            with ad.model.bind_state(params, buffers):
                h, new_caches = ad.call(Tensor(ids), local)
                h_last = jax.lax.dynamic_slice_in_dim(
                    h._data, true_len - 1, 1, axis=1)
                logits = ad.head(Tensor(h_last))._data[0, -1]
            splice = lambda pool, c: jax.lax.dynamic_update_slice(
                pool, getattr(c, "_data", c).astype(pool.dtype),
                (slot, 0, 0, 0))
            ks = [splice(p, c[0]) for p, c in zip(ks, new_caches)]
            vs = [splice(p, c[1]) for p, c in zip(vs, new_caches)]
            return logits, ks, vs

        self._prefill_jit = jax.jit(pure,
                                    donate_argnums=self._donate())
        return self._prefill_jit

    def _decode_fn(self):
        """THE decode-step program (compiled once): every occupied slot
        advances one token at its own position; the active-slot mask
        pins inactive lanes to position 0 and zeroes their logits so
        they stay numerically inert whatever garbage their row holds."""
        if self._decode_jit is not None:
            return self._decode_jit
        ad = self.adapter

        def pure(params, buffers, toks, pos, active, ks, vs):
            self.trace_counts["decode"] += 1
            pos_eff = jnp.where(active, pos, 0).astype(jnp.int32)
            caches = [(k, v, pos_eff) for k, v in zip(ks, vs)]
            with ad.model.bind_state(params, buffers):
                h, new_caches = ad.call(Tensor(toks), caches)
                logits = ad.head(h[:, -1:])._data[:, -1]
            logits = jnp.where(active[:, None], logits, 0.0)
            ks2 = [getattr(c[0], "_data", c[0]) for c in new_caches]
            vs2 = [getattr(c[1], "_data", c[1]) for c in new_caches]
            return logits, ks2, vs2

        self._decode_jit = jax.jit(pure,
                                   donate_argnums=self._donate())
        return self._decode_jit

    @staticmethod
    def _donate():
        """Donate the cache pools (args 5/6 of both programs) so the
        update is in-place on device; CPU ignores donation and warns,
        so skip it there."""
        return () if jax.default_backend() == "cpu" else (5, 6)
