"""Multi-replica request router: health-driven dispatch, draining,
and failover with exactly-once delivery through replica death.

A single :class:`~paddle_tpu.serving.engine.ServingEngine` process is
a single point of failure — the resilience machinery below it
(``recover()``, typed errors, conservation auditing) survives a failed
*step*, but not a dead *replica*. The router closes that gap: it
spreads requests across N engine replicas (least-loaded dispatch,
FCFS within a replica) and keeps serving through whole-replica death:

- **Health-driven draining.** Every ``step()`` round probes each
  replica first. One failed probe marks the replica SUSPECT — it
  keeps serving its in-flight work but receives no new dispatches
  (draining); ``probe_fail_threshold`` consecutive failures, or a
  :class:`ReplicaDead` raised from a probe or a step, declare it DEAD.
- **Failover = adoption.** A dead replica's requests are re-homed from
  the router's own bookkeeping (the host-side ``Request`` objects it
  dispatched): terminal requests the replica finished but never
  returned are delivered now; everything else is ``adopt()``-ed by a
  live peer, whose admission path re-prefills prompt + already-
  delivered tokens via the ``recover()`` replay contract — greedy
  outputs stay token-identical through the death, and no delivered
  token is ever retracted. With no live peer left, requests are
  cancelled (typed error attached) rather than stranded.
- **Exactly-once.** The router delivers a request to its caller
  exactly once: every path out (step return, recover report, failover,
  drain) funnels through one ``_deliver`` gate keyed on the router's
  in-flight table. The chaos harness audits this end-to-end with the
  :class:`~paddle_tpu.resilience.invariants.ConservationLedger`
  mounted at the front door (``serving/frontdoor.py``) — replica-kill
  episodes in ``resilience/chaos.py`` certify the failover path
  instead of trusting it.
- **Step-failure policy.** A replica whose step raises with a broken
  engine (donated pools) gets ``recover()`` — the single-engine
  machinery, reused per replica; repeated recover failures or repeated
  transient step failures escalate to death + failover.

Fault points (``resilience.faults``): ``router.dispatch`` fires in
``submit()`` before a request is bound to a replica (a dispatch-path
crash is a typed refusal to the caller — the request is never half-
submitted); ``router.health_probe`` fires inside the probe (probe
infrastructure failures must degrade to draining, not lose requests).

The router is drive-compatible with the engine (``submit / step /
has_work / cancel / drain``), so the front door serves one engine or
N replicas through the same loop.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..observability import (TraceContext, default_recorder,
                             default_registry, span)
from ..resilience.faults import maybe_fail
from .errors import (EngineClosed, NoHealthyReplicas, ReplicaDead,
                     RequestCancelled)
from .scheduler import Request
from .sampling import SamplingParams

__all__ = ["Replica", "ReplicaRouter", "death_kind",
           "HEALTHY", "SUSPECT", "DEAD", "RETIRED"]

# free-text death reasons (which embed exception strings) normalized
# to a bounded label set before they reach a metric label or span
# attr — the registry's cardinality guard would otherwise trip on the
# embedded message text. Order matters two ways: "unreachable" is
# checked FIRST because retry exhaustion is a root cause, not a
# symptom — a partition surfaces through whatever RPC happens to run
# next ("died mid-step: ... unreachable after retries ..."), and the
# network fault must win over the router-level wrapper so watchtower
# can tell a partition from a worker death; among the rest, the
# router-level classification wins over the wrapped ReplicaDead
# message it embeds.
_DEATH_KINDS = (
    ("unreachable", "unreachable"),
    ("probe failures", "probe_failures"),
    ("step failures", "step_failures"),
    ("recover() failed", "recover_failed"),
    ("died mid-step", "died_mid_step"),
    ("died during drain", "died_during_drain"),
    ("process gone", "process_gone"),
    ("process exited", "process_exited"),
)


def death_kind(reason: str) -> str:
    """Normalize a free-text replica-death reason to a bounded set."""
    r = str(reason)
    for sub, kind in _DEATH_KINDS:
        if sub in r:
            return kind
    return "other"

HEALTHY = "healthy"    # probed clean: dispatchable
SUSPECT = "suspect"    # failed probe(s): draining, no new dispatches
DEAD = "dead"          # failed over; its engine is never touched again
RETIRED = "retired"    # drained empty on request and removed cleanly


class Replica:
    """One engine replica under the router: the engine plus the
    router's health view of it."""

    def __init__(self, replica_id: str, engine):
        self.id = str(replica_id)
        self.engine = engine
        self.state = HEALTHY
        self.alive = True          # chaos kill switch (process death)
        self.probe_failures = 0
        self.step_failures = 0
        self.recover_failures = 0

    def kill(self) -> None:
        """Simulate whole-replica death (chaos: the process is gone).
        The next probe or step raises :class:`ReplicaDead` and the
        router fails its requests over to peers."""
        self.alive = False

    @property
    def dispatchable(self) -> bool:
        return self.state == HEALTHY

    @property
    def live(self) -> bool:
        return self.state in (HEALTHY, SUSPECT)

    def load(self) -> int:
        """Queued + in-flight request count (dispatch weight)."""
        eng = self.engine
        return eng.scheduler.depth + len(eng.cache.active_slots())


class ReplicaRouter:
    """Spread requests over N engine replicas; survive replica death
    (see module docstring). Engine-shaped driving surface."""

    RID_BASE = 1 << 30



    def __init__(self, engines, *, registry=None, flight_recorder=None,
                 auditor=None,
                 probe_fail_threshold: int = 2,
                 step_fail_threshold: int = 3,
                 recover_fail_threshold: int = 3,
                 probe_timeout_s: Optional[float] = 1.0,
                 affinity=None):
        if not engines:
            raise ValueError("ReplicaRouter needs at least one engine")
        # pre-built Replica objects pass through (the cluster
        # supervisor registers RemoteReplica subclasses); bare engines
        # are wrapped with positional ids
        self.replicas = [e if isinstance(e, Replica) else
                         Replica(str(i), e)
                         for i, e in enumerate(engines)]
        ids = [r.id for r in self.replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {sorted(ids)}")
        self.registry = registry if registry is not None \
            else default_registry()
        self.recorder = flight_recorder if flight_recorder is not None \
            else default_recorder()
        # auditor for STANDALONE router use; under a FrontDoor the
        # ledger mounts there instead and this stays None
        self.auditor = auditor
        # serving.control.PrefixAffinityPolicy (optional): dispatch
        # prefers the replica whose radix index already holds the
        # request's prefix; least-loaded remains the fallback and the
        # only policy for dead/draining candidates
        self.affinity = affinity
        self.probe_fail_threshold = int(probe_fail_threshold)
        self.step_fail_threshold = int(step_fail_threshold)
        self.recover_fail_threshold = int(recover_fail_threshold)
        # per-probe time budget, DISTINCT from the DEAD threshold: a
        # probe that exceeds it raises TimeoutError and takes the
        # transient path (SUSPECT → drain), so ONE hung RPC never
        # triggers an instant failover. None = unbounded probes.
        self.probe_timeout_s = probe_timeout_s
        # router rids live in their own namespace, above anything an
        # engine's private counter (0, 1, ...) can reach, so a direct
        # engine.submit() on a routed engine can never mint a rid that
        # collides with a routed request in the exactly-once gate
        # (kept below the RandomState seed cap: 0x5EED + rid < 2**32)
        self._next_rid = self.RID_BASE
        self._closed = False
        # delivery sink for requests surfacing outside a step()/drain()
        # round (e.g. cancel(), failover during probes); step() swaps
        # its own list in and detaches it on exit
        self._pending_out: List[Request] = []
        # rid -> Request for everything accepted and not yet delivered:
        # THE exactly-once gate — _deliver() pops it, and a request
        # that is not in it cannot surface to the caller again
        self._inflight: Dict[int, Request] = {}
        self._owner: Dict[int, str] = {}            # rid -> replica id
        reg = self.registry
        self._m_healthy = reg.gauge(
            "ptpu_router_replica_healthy",
            "1 = replica dispatchable, 0 = draining/dead",
            labels=("replica",))
        self._m_inflight = reg.gauge(
            "ptpu_router_replica_inflight",
            "queued + in-slot requests on this replica",
            labels=("replica",))
        self._m_dispatch = reg.counter(
            "ptpu_router_dispatches_total",
            "requests dispatched to this replica",
            labels=("replica",))
        self._m_failover = reg.counter(
            "ptpu_router_failovers_total",
            "replica deaths the router failed over")
        self._m_failover_req = reg.counter(
            "ptpu_router_failover_requests_total",
            "requests re-homed to a peer after a replica death")
        self._m_deaths = reg.counter(
            "ptpu_router_replica_deaths_total",
            "replica deaths by normalized reason (death_kind)",
            labels=("reason",))
        for rep in self.replicas:
            self._m_healthy.labels(replica=rep.id).set(1)
            self._m_inflight.labels(replica=rep.id).set(0)

    # -- cancel-probe pass-through (front door installs one) ----------
    @property
    def cancel_probe(self):
        return self.replicas[0].engine.cancel_probe

    @cancel_probe.setter
    def cancel_probe(self, probe) -> None:
        for rep in self.replicas:
            rep.engine.cancel_probe = probe

    # -- dispatch ------------------------------------------------------
    def _pick_replica(self, prompt_ids=None) -> Replica:
        cands = [r for r in self.replicas if r.dispatchable]
        if not cands:
            raise NoHealthyReplicas(len(self.replicas))
        fallback = min(cands, key=lambda r: (r.load(), r.id))
        if self.affinity is not None and prompt_ids is not None:
            return self.affinity.pick(cands, prompt_ids, fallback)
        return fallback

    def submit(self, prompt_ids, max_new_tokens: int = 16,
               sampling: Optional[SamplingParams] = None,
               deadline_s: Optional[float] = None,
               tenant: Optional[str] = None) -> Request:
        """Dispatch one request to the least-loaded healthy replica.
        Typed refusals: :class:`NoHealthyReplicas`,
        :class:`EngineClosed` after ``drain()``, plus whatever the
        target engine's admission raises (``QueueFull`` etc.)."""
        if self._closed:
            raise EngineClosed()
        target = self._pick_replica(prompt_ids)
        maybe_fail("router.dispatch", replica=target.id)
        req = target.engine._build_request(
            prompt_ids, max_new_tokens, sampling, deadline_s,
            rid=self._next_rid, tenant=tenant)
        # mint the distributed trace BEFORE the dispatch RPC: the
        # context rides the pickled request to the worker, and the
        # dispatch span (ctx=) stamps it on the RPC frame too
        req.trace = TraceContext.for_request(req.rid)
        with span("router.dispatch", request_id=req.rid,
                  replica=target.id, ctx=req.trace):
            target.engine.submit_request(req)
        self._next_rid += 1
        self._inflight[req.rid] = req
        self._owner[req.rid] = target.id
        self._m_dispatch.labels(replica=target.id).inc()
        self._m_inflight.labels(replica=target.id).set(target.load())
        if self.auditor is not None:
            self.auditor.on_submitted(req)
        return req

    def has_work(self) -> bool:
        return any(r.live and r.engine.has_work()
                   for r in self.replicas)

    def add_replica(self, engine, replica_id: Optional[str] = None):
        """Register a fresh replica on a RUNNING router (the cluster
        supervisor's respawn path; also hot capacity adds). Accepts a
        bare engine or a pre-built :class:`Replica`; the new replica
        inherits the installed ``cancel_probe`` and is dispatchable
        immediately. Typed :class:`EngineClosed` after ``drain()``."""
        if self._closed:
            raise EngineClosed()
        if isinstance(engine, Replica):
            rep = engine
        else:
            rep = Replica(replica_id if replica_id is not None
                          else str(len(self.replicas)), engine)
        if any(r.id == rep.id for r in self.replicas):
            raise ValueError(
                f"replica id {rep.id!r} already registered")
        probe = None
        try:
            probe = self.replicas[0].engine.cancel_probe
        except Exception:
            pass
        if probe is not None:
            rep.engine.cancel_probe = probe
        self.replicas.append(rep)
        self._m_healthy.labels(replica=rep.id).set(1)
        self._m_inflight.labels(replica=rep.id).set(0)
        self.recorder.record("router.replica_added", replica=rep.id)
        return rep

    # -- health --------------------------------------------------------
    def probe(self, rep: Replica) -> bool:
        """One health probe: True = clean. Raises nothing; state
        transitions (SUSPECT / DEAD + failover) happen inside."""
        if not rep.live:
            return False
        try:
            maybe_fail("router.health_probe", replica=rep.id)
            if not rep.alive:
                raise ReplicaDead(f"replica {rep.id} health probe: "
                                  f"process gone")
            # engines with a real liveness check (remote replicas: one
            # RPC) answer within the probe budget. SLOW is not DEAD:
            # a TimeoutError lands in the generic arm below — SUSPECT
            # first, DEAD only after probe_fail_threshold repeats.
            # Only a torn connection (ReplicaDead) kills instantly.
            probe_fn = getattr(rep.engine, "probe", None)
            if probe_fn is not None:
                probe_fn(timeout=self.probe_timeout_s)
        except ReplicaDead as e:
            self._mark_dead(rep, str(e))
            return False
        except Exception as e:  # probe infrastructure failure
            rep.probe_failures += 1
            if rep.probe_failures >= self.probe_fail_threshold:
                self._mark_dead(
                    rep, f"{rep.probe_failures} consecutive probe "
                         f"failures ({type(e).__name__}: {e})")
            else:
                # draining: keep serving in-flight work, stop feeding
                rep.state = SUSPECT
                self._m_healthy.labels(replica=rep.id).set(0)
            return False
        rep.probe_failures = 0
        if rep.state == SUSPECT:
            rep.state = HEALTHY
            self._m_healthy.labels(replica=rep.id).set(1)
        return True

    def _mark_dead(self, rep: Replica, reason: str) -> None:
        if rep.state == DEAD:
            return
        rep.state = DEAD
        rep.alive = False
        self._m_healthy.labels(replica=rep.id).set(0)
        self._m_inflight.labels(replica=rep.id).set(0)
        kind = death_kind(reason)
        self._m_failover.inc()
        self._m_deaths.labels(reason=kind).inc()
        self.recorder.record("router.replica_dead", replica=rep.id,
                             reason=reason)
        with span("router.failover", replica=rep.id, reason=kind):
            self._failover(rep)

    def _failover(self, rep: Replica) -> None:
        """Re-home everything a dead replica held. The replica's
        engine host state is read ONE last time (and cleared, so the
        dead replica is inert afterwards); its device pools are
        considered gone with the process."""
        eng = rep.engine
        orphans: List[Request] = []
        # terminal debt a failed step stranded: finished, never
        # returned — deliver it now, exactly once
        orphans.extend(eng._undelivered)
        eng._undelivered = []
        orphans.extend(eng.scheduler.drain())
        for s in list(eng.cache.active_slots()):
            req = eng.cache.slots[s]
            try:
                eng.cache.release(s)
            except Exception:
                pass          # dying bookkeeping must not stop failover
            req.slot = None
            orphans.append(req)
        seen = set()
        for req in orphans:
            if req.rid in seen:
                continue
            seen.add(req.rid)
            if req.finished:
                self._deliver(req, self._pending_out)
                continue
            peer = self._adopt_elsewhere(req, from_replica=rep.id)
            if peer is None:
                req.finished, req.finish_reason = True, "cancelled"
                req.error = RequestCancelled(
                    req.rid, f"replica {rep.id} died with no live "
                             f"peer to adopt its requests")
                self._deliver(req, self._pending_out)
            else:
                self._owner[req.rid] = peer.id
                self._m_failover_req.inc()

    def _adopt_elsewhere(self, req: Request,
                         from_replica: Optional[str] = None
                         ) -> Optional[Replica]:
        cands = sorted((r for r in self.replicas if r.live),
                       key=lambda r: (r.state != HEALTHY, r.load(),
                                      r.id))
        # the annotated failover span: in the merged timeline it sits
        # on the router lane between the request's two worker lanes,
        # and the chrome-trace flow arrows hang off it
        with span("router.failover.rehome", request_id=req.rid,
                  ctx=getattr(req, "trace", None),
                  from_replica=from_replica) as sp:
            for rep in cands:
                try:
                    rep.engine.adopt(req)
                    sp.set_attr("to_replica", rep.id)
                    return rep
                except Exception:
                    continue
            sp.set_attr("to_replica", None)
            return None

    # -- the serving loop ---------------------------------------------
    def step(self) -> List[Request]:
        """One router round: probe every replica, then one engine
        iteration per live replica (recover / escalate to failover on
        failures). Returns every request delivered this round. Never
        raises out of a replica failure — a replica that cannot be
        saved is failed over, not surfaced as an exception."""
        out: List[Request] = []
        # _pending_out: delivery sink for requests surfacing OUTSIDE a
        # step (failover during submit-time probes would have no list
        # to land in) — step() always flushes it first
        self._pending_out = out
        for rep in list(self.replicas):
            self.probe(rep)
        for rep in self.replicas:
            if not rep.live or not rep.engine.has_work():
                continue
            try:
                done = rep.engine.step()
                rep.step_failures = 0
            except ReplicaDead as e:
                self._mark_dead(rep, f"died mid-step: {e}")
                continue
            except Exception as e:
                if rep.engine._broken:
                    try:
                        done = rep.engine.recover()["finished"]
                        rep.recover_failures = 0
                    except Exception as re:
                        rep.recover_failures += 1
                        if rep.recover_failures \
                                >= self.recover_fail_threshold:
                            self._mark_dead(
                                rep, f"recover() failed "
                                     f"{rep.recover_failures}x "
                                     f"({type(re).__name__}: {re})")
                        continue
                else:
                    # transient: the faulted request was re-queued by
                    # the engine; retry next round, escalate if it
                    # keeps happening
                    rep.step_failures += 1
                    if rep.step_failures >= self.step_fail_threshold:
                        self._mark_dead(
                            rep, f"{rep.step_failures} consecutive "
                                 f"step failures "
                                 f"({type(e).__name__}: {e})")
                    continue
            for req in done:
                self._deliver(req, out)
            self._m_inflight.labels(replica=rep.id).set(rep.load())
        self._pending_out = []       # detach the sink
        return out

    def _deliver(self, req: Request, out: List[Request]) -> None:
        """THE exactly-once gate: a request leaves the router at most
        once, whatever combination of step returns, recover reports,
        failovers and drains it rode through. Popped by OBJECT
        identity (adoption moves the same Request between engines), so
        a foreign request — e.g. someone drove engine.submit() behind
        the router's back — can never evict a routed request's
        entry."""
        if self._inflight.get(req.rid) is not req:
            return
        del self._inflight[req.rid]
        self._owner.pop(req.rid, None)
        out.append(req)
        if self.auditor is not None:
            self.auditor.on_delivered(req, via="router")

    def cancel(self, req: Request, reason: str = "cancelled") -> bool:
        """Cancel one request wherever it lives; False if it already
        finished (or was never ours)."""
        if req.rid not in self._inflight:
            return False
        owner = self._owner.get(req.rid)
        rep = next((r for r in self.replicas if r.id == owner), None)
        if rep is not None and rep.live \
                and rep.engine.cancel(req, reason):
            self._deliver(req, self._pending_out)
            return True
        return False

    def drain_replica(self, replica_id: str) -> None:
        """Gracefully take one replica out of rotation: its QUEUED
        requests move to peers now, its in-flight slots finish under
        the normal step loop, and once empty it is RETIRED (never
        dispatched again). The service keeps serving throughout —
        this is the rolling-restart primitive."""
        rep = next(r for r in self.replicas if r.id == replica_id)
        if not rep.live:
            return
        rep.state = SUSPECT
        self._m_healthy.labels(replica=rep.id).set(0)
        for req in rep.engine.scheduler.drain():
            peer = self._adopt_elsewhere(req, from_replica=rep.id)
            if peer is not None:
                self._owner[req.rid] = peer.id
            else:                      # nowhere to go: put it back
                rep.engine.scheduler.requeue(req)
        rep.state = RETIRED if not rep.engine.has_work() else SUSPECT

    def step_until_retired(self, replica_id: str,
                           max_steps: int = 1000) -> List[Request]:
        """Drive step() until a draining replica empties, then retire
        it. Returns everything delivered along the way."""
        rep = next(r for r in self.replicas if r.id == replica_id)
        out: List[Request] = []
        steps = 0
        while rep.live and rep.engine.has_work() \
                and steps < max_steps:
            out.extend(self.step())
            steps += 1
        if rep.live and not rep.engine.has_work():
            rep.state = RETIRED
            self._m_healthy.labels(replica=rep.id).set(0)
        return out

    def drain(self, max_steps: Optional[int] = None) -> List[Request]:
        """Graceful shutdown composed across replicas: refuse new
        submissions, drain every live replica (each engine's own
        ``drain()`` semantics: serve what it can, cancel the rest at
        the cutoff), then cancel anything still tracked (dead-replica
        stragglers that had no peer). Returns every request delivered
        or cancelled — and like the engine, never raises mid-loop."""
        self._closed = True
        out: List[Request] = []
        self._pending_out = out
        for rep in list(self.replicas):
            if not rep.live:
                continue
            try:
                done = rep.engine.drain(max_steps)
            except Exception as e:
                # a replica dying DURING shutdown must not abort the
                # drain of its peers: fail it over (adoption lands on
                # peers not yet drained, or the straggler sweep below
                # cancels typed) and keep going
                self._mark_dead(rep, f"died during drain: "
                                     f"{type(e).__name__}: {e}")
                continue
            for req in done:
                self._deliver(req, out)
            self._m_inflight.labels(replica=rep.id).set(0)
        for req in list(self._inflight.values()):
            if not req.finished:
                req.finished, req.finish_reason = True, "cancelled"
                req.error = RequestCancelled(
                    req.rid, "router drain: no replica could serve "
                             "this request")
            self._deliver(req, out)
        self._pending_out = []
        return out

    # -- introspection -------------------------------------------------
    def health(self) -> Dict[str, Dict[str, object]]:
        """Per-replica snapshot for /healthz and dashboards."""
        return {rep.id: {"state": rep.state,
                         "load": rep.load() if rep.live else 0,
                         "probe_failures": rep.probe_failures}
                for rep in self.replicas}
