"""Cross-process serving cluster: RPC replicas + a reaping supervisor.

The :class:`~paddle_tpu.serving.router.ReplicaRouter` is certified
in-process (PR 7); this module puts a *process boundary* under the
same state machine without changing it. Three pieces:

- :class:`RemoteEngine` — an engine-shaped RPC client for one worker
  process (``serving/worker.py``). The router drives replicas through
  the engine surface (``submit_request / step / probe / adopt / drain
  / cancel / recover``) *and* reads engine host state directly during
  failover (``scheduler``, ``cache``, ``_undelivered``, ``_broken``)
  — so the client IS an engine-shaped object: every RPC response
  refreshes a host-side **mirror** (the authoritative ``Request``
  objects the router tracks by identity, plus queue order / slot map),
  and when the worker dies the router's ``_failover`` re-homes
  everything from the mirror exactly as it would from a local engine.
  Per-call deadlines, :class:`~paddle_tpu.resilience.retry.RetryPolicy`
  backoff on transient socket errors (resends are dedup'd worker-side
  by ``(token, seq)``, so retries never double-execute), and typed
  :class:`~paddle_tpu.serving.errors.ReplicaDead` when the connection
  is gone for good. A *slow* worker is not a dead one: a probe that
  exceeds its timeout budget raises ``TimeoutError`` — the router
  marks SUSPECT (drain) and only escalates on repetition.
- :class:`RemoteReplica` — ``Replica`` subclass pairing the client
  with its process handle (pid/poll for the supervisor).
- :class:`ClusterSupervisor` — spawns workers (TCPStore rendezvous),
  builds the router over their clients, and ``poll()``-s the cluster:
  a replica the router declared DEAD is *reaped* (its process
  SIGKILLed if still running — fencing: a partitioned worker must not
  keep computing into pools nobody reads) and *respawned* (a warm
  process is re-armed with a ``reset`` RPC; an exited one is
  re-spawned), bounded by ``max_respawns`` → typed
  :class:`~paddle_tpu.resilience.train_loop.RestartLimitExceeded`;
  the fresh replica re-registers with the running router via
  ``router.add_replica``. ``new_episode()`` re-arms the whole cluster
  (fresh engines + fresh router over warm processes) so a chaos band
  amortizes process spawns across seeds.

Trust boundary: every cluster connection runs the shared-secret HMAC
handshake + per-frame MAC from ``distributed/_framing.py`` (secret via
``PTPU_CLUSTER_SECRET`` or the ``secret=`` kwarg; the supervisor
generates one per cluster when neither is given and hands it to
spawned workers through their environment — never argv, never the
store). TCPStore rendezvous values ride sealed HMAC envelopes and the
worker spec is unpickled under a data-only allowlist, so a tampered
rendezvous or an unauthenticated client is a counted, typed rejection
(``ptpu_cluster_auth_failures_total``) — not code execution. Bind and
advertise addresses are configurable (``bind_host``/``advertise_host``)
so workers can live on other hosts; RPC *payloads* between
authenticated peers are still pickle, so the secret is the perimeter.
"""
from __future__ import annotations

import json
import os
import pickle
import secrets
import signal
import socket
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ..distributed._framing import (client_handshake, nodelay,
                                    open_sealed, recv_msg,
                                    register_auth_failure_hook, seal,
                                    send_msg)
from ..observability import (TraceBuffer, active_context,
                             default_recorder, default_registry,
                             install_trace_buffer)
from ..resilience.retry import RetryError, RetryPolicy
from ..resilience.train_loop import RestartLimitExceeded
from .errors import ReplicaDead
from .router import DEAD, Replica, ReplicaRouter
from .sampling import SamplingParams
from .scheduler import Request

__all__ = ["RemoteEngine", "RemoteReplica", "ClusterSupervisor",
           "WorkerHandle", "normalize_op"]

# every op the protocol speaks; anything else (a future/misspelled op
# would otherwise mint a fresh metric label per value) collapses to
# "other" before reaching the latency histogram's label set
_RPC_OPS = frozenset({
    "probe", "submit", "adopt", "step", "recover", "drain", "cancel",
    "unqueue", "requeue", "audit", "reset", "stall", "arm",
    "telemetry", "shutdown"})


def normalize_op(op: str) -> str:
    """Bound RPC op names to the known protocol set for labels."""
    return op if op in _RPC_OPS else "other"


# one process-wide bridge from _framing's auth-failure hook to the
# registry counter: registered once at import, pointed at whichever
# registry most recently built the counter (the hook list in _framing
# dedups by identity, so N supervisors in one test process never
# double-count a single rejection)
_AUTH_COUNTER = {"c": None}


def _publish_auth_failure(_reason: str) -> None:
    c = _AUTH_COUNTER["c"]
    if c is not None:
        try:
            c.inc()
        except Exception:
            pass


def _ensure_auth_counter(reg) -> None:
    _AUTH_COUNTER["c"] = reg.counter(
        "ptpu_cluster_auth_failures_total",
        "typed auth rejections: failed handshakes, bad/replayed frame "
        "MACs, tampered rendezvous values, disallowed spec globals")
    register_auth_failure_hook(_publish_auth_failure)


def resolve_secret(secret=None) -> bytes:
    """The cluster shared secret as bytes: the explicit argument, else
    ``PTPU_CLUSTER_SECRET``, else a fresh random one (single-process
    clusters that never export the env var still authenticate)."""
    if secret:
        return secret if isinstance(secret, bytes) \
            else str(secret).encode("utf-8")
    env = os.environ.get("PTPU_CLUSTER_SECRET", "")
    if env:
        return env.encode("utf-8")
    return secrets.token_hex(32).encode("ascii")


# ---------------------------------------------------------------------------
# host-side mirrors: the engine-shaped state the router reads directly
# ---------------------------------------------------------------------------

class _MirrorScheduler:
    """FIFO view of the worker's admission queue, in rid order."""

    def __init__(self, client: "RemoteEngine"):
        self._c = client

    @property
    def depth(self) -> int:
        return len(self._c._queued)

    def has_pending(self) -> bool:
        return bool(self._c._queued)

    def pending(self) -> List[Request]:
        reqs = self._c._reqs
        return [reqs[rid] for rid in self._c._queued if rid in reqs]

    def drain(self) -> List[Request]:
        """Take every queued request (failover / drain_replica). When
        the worker is still reachable it is told to drop them too —
        otherwise a rolling restart would leave the queue double-owned;
        when it is not (that's the failover path), local state IS the
        truth and this must never raise."""
        out = self.pending()
        if out and not self._c._dead:
            try:
                self._c._call("unqueue", retry=False)
                # _apply already rebuilt the mirror from the response
            except Exception:
                pass
        for r in out:
            self._c._reqs.pop(r.rid, None)
        self._c._queued = [rid for rid in self._c._queued
                           if rid in self._c._reqs]
        return out

    def requeue(self, req: Request) -> None:
        if not self._c._dead:
            try:
                self._c._call("requeue", {"req": req}, retry=False)
                return
            except Exception:
                pass
        self._c._reqs[req.rid] = req
        self._c._queued.insert(0, req.rid)


class _MirrorCache:
    """Slot map view; ``slots`` indexes by slot id like the real one."""

    def __init__(self, client: "RemoteEngine"):
        self._c = client

    @property
    def slots(self) -> Dict[int, Request]:
        reqs = self._c._reqs
        return {s: reqs[rid] for s, rid in self._c._slots.items()
                if rid in reqs}

    def active_slots(self) -> List[int]:
        return [s for s, rid in self._c._slots.items()
                if rid in self._c._reqs]

    def release(self, s: int) -> None:
        self._c._slots.pop(s, None)


# ---------------------------------------------------------------------------
# the RPC client
# ---------------------------------------------------------------------------

class RemoteEngine:
    """Engine-shaped client for one worker process (module doc)."""

    def __init__(self, host: str, port: int, *, name: str = "worker",
                 engine_kw: Optional[Dict[str, Any]] = None,
                 time_fn: Callable[[], float] = time.monotonic,
                 registry=None,
                 call_deadline_s: float = 30.0,
                 step_deadline_s: float = 180.0,
                 probe_timeout_s: Optional[float] = None,
                 proc: Optional[subprocess.Popen] = None,
                 secret: Optional[bytes] = None):
        self.host, self.port, self.name = host, int(port), name
        # None = legacy unauthenticated framing (standalone tests);
        # the supervisor ALWAYS passes the cluster secret
        self._secret = secret
        self._auth = None
        ekw = dict(engine_kw or {})
        # the validation surface _build_request needs, mirrored from
        # the spec so admission errors are raised host-side and typed
        self.max_slots = int(ekw.get("max_slots", 8))
        self.max_len = int(ekw.get("max_len", 0)) or None
        self.min_bucket = int(ekw.get("min_bucket", 16))
        self.max_queue = ekw.get("max_queue")
        # leak audits on the *client* object see an unpaged,
        # non-speculative mirror; the real engine's page/handoff laws
        # are audited worker-side via remote_audit()
        self.paged = False
        self.speculative = False
        self.meshctx = None
        self.cancel_probe = None
        self._now = time_fn
        self._proc = proc
        self._call_deadline = float(call_deadline_s)
        self._step_deadline = float(step_deadline_s)
        self._probe_deadline = float(
            probe_timeout_s if probe_timeout_s is not None
            else call_deadline_s)
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        self._token = uuid.uuid4().hex   # resend-dedup namespace
        self._dead = False
        # the FIRST fatal cause ("unreachable after retries", "process
        # exited with ..."): every later marked-dead raise carries it,
        # so death_kind (and watchtower's partition-vs-death
        # classification) see the root cause, not the fencing symptom
        self._dead_reason = ""
        self._reqs: Dict[int, Request] = {}
        self._queued: List[int] = []
        self._slots: Dict[int, int] = {}
        self._undelivered: List[Request] = []
        self._broken: Optional[str] = None
        self.worker_pid: Optional[int] = None
        self.scheduler = _MirrorScheduler(self)
        self.cache = _MirrorCache(self)
        reg = registry if registry is not None else default_registry()
        if secret is not None:
            _ensure_auth_counter(reg)
        self._m_latency = reg.histogram(
            "ptpu_cluster_rpc_latency_seconds",
            "wall time of one cluster RPC (incl. retries)",
            labels=("op",))
        self._m_inflight = reg.gauge(
            "ptpu_cluster_worker_rpc_inflight",
            "1 while an RPC to this worker is on the wire",
            labels=("worker",))
        self._retry = RetryPolicy(
            max_attempts=3, base_delay=0.02, max_delay=0.25,
            retry_on=(ConnectionError, OSError),
            no_retry_on=(TimeoutError,), seed=0)

    # -- wire ----------------------------------------------------------
    def _close_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        # auth session state (keys + frame counters) dies with the
        # socket; the next _attempt re-handshakes on the fresh one
        self._auth = None

    def _attempt(self, blob: bytes, seq: int, deadline: float) -> dict:
        if self._proc is not None and self._proc.poll() is not None:
            raise ReplicaDead(
                f"worker {self.name} process exited with "
                f"{self._proc.returncode}")
        if self._sock is None:
            sock = nodelay(socket.create_connection(
                (self.host, self.port), timeout=min(deadline, 5.0)))
            try:
                if self._secret is not None:
                    self._auth = client_handshake(sock, self._secret)
            except Exception:
                try:
                    sock.close()
                except OSError:
                    pass
                raise
            self._sock = sock
        self._sock.settimeout(deadline)
        try:
            send_msg(self._sock, blob, auth=self._auth)
            resp_blob = recv_msg(self._sock, auth=self._auth)
        except Exception:
            # after any wire error the stream position is undefined
            # (see _framing): the socket must die with the attempt
            self._close_sock()
            raise
        resp = pickle.loads(resp_blob)
        if resp.get("seq") != seq:
            self._close_sock()
            raise ConnectionError(
                f"rpc seq desync (sent {seq}, got {resp.get('seq')})")
        return resp

    def _call(self, op: str, payload: Optional[dict] = None,
              deadline: Optional[float] = None,
              retry: bool = True) -> dict:
        if self._dead:
            raise ReplicaDead(
                f"worker {self.name} marked dead"
                + (f" ({self._dead_reason})" if self._dead_reason
                   else ""))
        self._seq += 1
        seq = self._seq
        # every frame carries the virtual clock AND the active trace
        # context (the span enclosing this call, e.g. router.dispatch)
        # so worker-side spans clock-align and parent correctly
        msg = {"op": op, "seq": seq, "token": self._token,
               "now": self._now(), "trace": active_context()}
        if payload:
            msg.update(payload)
        blob = pickle.dumps(msg)
        dl = float(deadline if deadline is not None
                   else self._call_deadline)
        t0 = time.monotonic()
        self._m_inflight.labels(worker=self.name).set(1)
        try:
            if retry:
                try:
                    resp = self._retry.call(self._attempt, blob, seq,
                                            dl, op=f"cluster.{op}")
                except RetryError as e:
                    self._dead = True
                    self._dead_reason = self._dead_reason \
                        or "unreachable after retries"
                    raise ReplicaDead(
                        f"worker {self.name} unreachable after "
                        f"retries ({e})") from e
            else:
                resp = self._attempt(blob, seq, dl)
        except ReplicaDead as e:
            self._dead = True
            self._dead_reason = self._dead_reason or e.detail \
                or str(e)
            raise
        finally:
            self._m_inflight.labels(worker=self.name).set(0)
            self._m_latency.labels(op=normalize_op(op)).observe(
                time.monotonic() - t0)
        self._apply(resp)
        if not resp.get("ok", False):
            err = resp.get("error") or ReplicaDead(
                f"worker {self.name} sent a malformed error response")
            raise err
        return resp

    def _apply(self, resp: dict) -> None:
        """Refresh the host-side mirror from a worker response."""
        for rid, u in (resp.get("updates") or {}).items():
            req = self._reqs.get(rid)
            if req is None:
                continue
            req.out_tokens[:] = u["out"]
            req.finished = u["finished"]
            req.finish_reason = u["reason"]
            req.error = u["error"]
            req.slot = u["slot"]
        st = resp.get("state")
        if st is not None:
            self._queued = [rid for rid in st["queued"]
                            if rid in self._reqs]
            self._slots = {s: rid for s, rid in st["slots"].items()
                           if rid in self._reqs}
            self._undelivered = [self._reqs[rid]
                                 for rid in st["undelivered"]
                                 if rid in self._reqs]
            self._broken = st["broken"]

    def _take_finished(self, resp: dict) -> List[Request]:
        out = []
        for rid in resp.get("finished") or ():
            req = self._reqs.pop(rid, None)
            if req is not None:
                out.append(req)
        self._queued = [r for r in self._queued if r in self._reqs]
        self._slots = {s: r for s, r in self._slots.items()
                       if r in self._reqs}
        return out

    def _cancel_rids(self) -> List[int]:
        """Client-side disconnect sweep: the FrontDoor flags *these*
        Request objects; ship the rids so the worker engine's own
        sweep runs the real abort paths (mid-prefill page unwind)."""
        rids = []
        probe = self.cancel_probe
        for rid, req in self._reqs.items():
            hit = req.cancel_requested
            if not hit and probe is not None:
                try:
                    hit = bool(probe(req))
                except Exception:
                    hit = False
            if hit:
                req.cancel_requested = True
                rids.append(rid)
        return rids

    # -- the engine surface the router drives --------------------------
    def _build_request(self, prompt_ids, max_new_tokens: int = 16,
                       sampling: Optional[SamplingParams] = None,
                       deadline_s: Optional[float] = None,
                       rid: Optional[int] = None,
                       tenant: Optional[str] = None) -> Request:
        # mirror of ServingEngine._build_request: validate HERE so a
        # bad request is a typed host-side refusal, never an RPC
        import numpy as np
        ids = np.asarray(getattr(prompt_ids, "numpy",
                                 lambda: prompt_ids)()).astype(np.int64)
        if ids.ndim == 2 and ids.shape[0] == 1:
            ids = ids[0]
        if ids.ndim != 1:
            raise ValueError(
                f"submit() takes a single prompt sequence; got shape "
                f"{ids.shape}. Call submit() once per request.")
        if ids.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if self.max_len is not None and \
                ids.size + max_new_tokens - 1 > self.max_len:
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens "
                f"({max_new_tokens}) - 1 exceeds max_len "
                f"{self.max_len}")
        sampling = sampling or SamplingParams()
        sampling.validate()
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0, got {deadline_s}")
        req = Request(rid=rid if rid is not None else 0,
                      prompt=ids, max_new_tokens=int(max_new_tokens),
                      sampling=sampling,
                      deadline=(self._now() + deadline_s
                                if deadline_s is not None else None),
                      tenant=tenant)
        req._rng = np.random.RandomState(
            sampling.seed if sampling.seed is not None
            else 0x5EED + req.rid)
        return req

    def submit_request(self, req: Request) -> Request:
        self._call("submit", {"req": req})
        self._reqs[req.rid] = req
        if req.rid not in self._queued:
            self._queued.append(req.rid)
        return req

    def adopt(self, req: Request) -> Request:
        self._call("adopt", {"req": req})
        self._reqs[req.rid] = req
        if req.rid not in self._queued:
            self._queued.append(req.rid)
        return req

    def has_work(self) -> bool:
        return bool(self._queued or self._slots)

    def probe(self, timeout: Optional[float] = None) -> dict:
        resp = self._call("probe", deadline=(
            timeout if timeout is not None else self._probe_deadline))
        self.worker_pid = resp.get("pid", self.worker_pid)
        return resp.get("health") or {}

    def step(self) -> List[Request]:
        payload = {"cancel_rids": self._cancel_rids()}
        resp = self._call("step", payload,
                          deadline=self._step_deadline)
        return self._take_finished(resp)

    def recover(self) -> dict:
        resp = self._call("recover", deadline=self._step_deadline)
        return {"finished": self._take_finished(resp)}

    def drain(self, max_steps: Optional[int] = None) -> List[Request]:
        resp = self._call("drain",
                          {"max_steps": max_steps,
                           "cancel_rids": self._cancel_rids()},
                          deadline=self._step_deadline)
        return self._take_finished(resp)

    def cancel(self, req: Request, reason: str = "cancelled") -> bool:
        resp = self._call("cancel", {"rid": req.rid, "reason": reason})
        self._take_finished(resp)
        return bool(resp.get("cancelled"))

    # -- cluster extras -------------------------------------------------
    def telemetry(self, deadline: Optional[float] = None) -> dict:
        """Scrape the worker's telemetry: its trace-buffer drain
        (+ the cumulative drain/drop counters the merger's loss
        detection needs), its clock, and a registry snapshot. A
        retried scrape returns the worker's cached response blob
        (resend dedup), never a second drain."""
        resp = self._call("telemetry", deadline=deadline)
        return resp.get("telemetry") or {}

    def remote_audit(self) -> List[str]:
        """Run the engine/page leak audits inside the worker (the
        mirror can't see device pools) and return the violations."""
        resp = self._call("audit")
        return list(resp.get("violations") or ())

    def reset(self, engine_kw: Optional[Dict[str, Any]] = None,
              donate: bool = False, virtual_clock: bool = False,
              deadline: Optional[float] = None) -> None:
        self._call("reset", {"engine": dict(engine_kw or {}),
                             "donate": donate,
                             "virtual_clock": virtual_clock},
                   deadline=deadline if deadline is not None
                   else self._call_deadline)
        self._reqs, self._queued, self._slots = {}, [], {}
        self._undelivered, self._broken = [], None
        if engine_kw:
            self.max_slots = int(engine_kw.get("max_slots",
                                               self.max_slots))
            self.max_len = int(engine_kw.get("max_len",
                                             self.max_len or 0)) or None
            self.min_bucket = int(engine_kw.get("min_bucket",
                                                self.min_bucket))

    def arm_fault(self, point: str, times: int = 1, after: int = 0,
                  kill: bool = False) -> None:
        self._call("arm", {"point": point, "times": times,
                           "after": after, "kill": kill})

    def stall(self, seconds: float,
              deadline: Optional[float] = None) -> None:
        self._call("stall", {"seconds": seconds}, deadline=deadline)

    def close(self) -> None:
        """Drop the connection without any RPC. The worker serves ONE
        connection at a time, so a superseded client (dead replica,
        previous episode) MUST close its socket or the next client
        waits in the listen backlog behind it."""
        self._dead = True
        self._close_sock()

    def shutdown(self) -> None:
        try:
            self._call("shutdown", retry=False, deadline=5.0)
        except Exception:
            pass
        self._close_sock()


class RemoteReplica(Replica):
    """A router replica whose engine lives in another process."""

    def __init__(self, replica_id: str, engine: RemoteEngine,
                 handle: "WorkerHandle"):
        super().__init__(replica_id, engine)
        self.handle = handle


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

class WorkerHandle:
    """One worker *slot*: the process currently filling it, plus the
    supervisor's bookkeeping. The slot label (``w<index>``) is stable
    across respawns; the worker id (``w<index>g<generation>``) names
    one process generation (store keys must not collide)."""

    def __init__(self, index: int):
        self.index = index
        self.generation = 0
        self.proc: Optional[subprocess.Popen] = None
        # replaced at rendezvous by the host the worker ADVERTISES
        # (sealed store value) — never assumed local
        self.host = "127.0.0.1"
        self.port: Optional[int] = None
        self.pid: Optional[int] = None
        self.client: Optional[RemoteEngine] = None
        self.replica: Optional[Replica] = None
        self.respawns = 0
        self.reaped = False

    @property
    def slot_label(self) -> str:
        return f"w{self.index}"

    @property
    def wid(self) -> str:
        return f"w{self.index}g{self.generation}"

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class ClusterSupervisor:
    """Spawn worker processes, route over them, reap + respawn the
    dead (module docstring). Lifecycle::

        sup = ClusterSupervisor(spec, n_workers=2, max_respawns=4)
        sup.start()              # spawn processes, build the router
        ...drive sup.router (submit/step/drain), call sup.poll()
           between rounds so dead workers respawn...
        sup.shutdown()

    ``spec`` (pickled to workers over the TCPStore): ``model_config``
    (+ ``tiny`` / ``model_seed``), ``engine`` (ServingEngine kwargs),
    ``virtual_clock``. ``new_episode()`` re-arms warm processes with
    fresh engines and a fresh router — the chaos band's per-seed
    entry point."""

    def __init__(self, spec: Dict[str, Any], *, n_workers: int = 2,
                 max_respawns: int = 2, respawn: bool = True,
                 registry=None, flight_recorder=None, auditor=None,
                 router_kwargs: Optional[Dict[str, Any]] = None,
                 client_kwargs: Optional[Dict[str, Any]] = None,
                 dump_on_death: bool = True,
                 spawn_timeout_s: float = 120.0,
                 telemetry=None, scrape_interval: int = 1,
                 spill_dir: Optional[str] = None,
                 spill_every: int = 8,
                 bind_host: str = "127.0.0.1",
                 advertise_host: Optional[str] = None,
                 secret=None,
                 weight_store_dir: Optional[str] = None):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        self.spec = dict(spec)
        # bind = the local interface sockets listen on (store + the
        # workers' RPC servers); advertise = the address peers dial.
        # They differ exactly when binding a wildcard/private interface
        # (bind 0.0.0.0, advertise the routable name).
        self.bind_host = str(bind_host)
        self.advertise_host = str(advertise_host or bind_host)
        self._secret = resolve_secret(secret)
        # shared weight store (serving/weight_store.py): when set, the
        # supervisor publishes the state dict once and workers load by
        # digest-verified fetch instead of rebuilding from the seed
        self._weight_store_dir = weight_store_dir
        # workers spill their flight ring here (flight_<pid>.json) so
        # a SIGKILL still leaves a post-mortem the death dump attaches
        self._spill_dir = spill_dir or tempfile.gettempdir()
        self.spec.setdefault("spill_dir", self._spill_dir)
        self.spec.setdefault("spill_every", int(spill_every))
        # observability.ClusterTelemetry (optional): the supervisor
        # scrapes every worker's telemetry RPC each `scrape_interval`
        # polls (and on death-reap) and feeds the merger
        self.telemetry = telemetry
        self.scrape_interval = int(scrape_interval)
        self._polls = 0
        self._host_buffer: Optional[TraceBuffer] = None
        self.n_workers = int(n_workers)
        self.max_respawns = int(max_respawns)
        self.respawn = bool(respawn)
        self.registry = registry if registry is not None \
            else default_registry()
        self.recorder = flight_recorder if flight_recorder is not None \
            else default_recorder()
        self.auditor = auditor
        self._router_kwargs = dict(router_kwargs or {})
        self._client_kwargs = dict(client_kwargs or {})
        self._dump_on_death = bool(dump_on_death)
        self._spawn_timeout = float(spawn_timeout_s)
        self._store = None
        self._prefix = f"cluster/{uuid.uuid4().hex[:8]}"
        self._slots: List[WorkerHandle] = []
        self.router: Optional[ReplicaRouter] = None
        self.respawns_used = 0
        self._episode = {"engine": dict(self.spec.get("engine") or {}),
                         "donate": bool(self.spec.get("donate")),
                         "virtual_clock":
                             bool(self.spec.get("virtual_clock"))}
        self._time_fn: Callable[[], float] = time.monotonic
        if self.telemetry is not None:
            self.telemetry.add_host_registry(self.registry,
                                             name="router")
            # router/dispatch spans land here; the lambda tracks
            # whatever clock the current episode installed
            self._host_buffer = TraceBuffer(
                time_fn=lambda: self._time_fn())
            install_trace_buffer(self._host_buffer)
        reg = self.registry
        _ensure_auth_counter(reg)
        self._m_alive = reg.gauge(
            "ptpu_cluster_worker_alive",
            "1 = worker process serving, 0 = reaped/down",
            labels=("worker",))
        self._m_worker_respawns = reg.gauge(
            "ptpu_cluster_worker_respawns",
            "respawns this worker slot has consumed",
            labels=("worker",))
        self._m_respawns = reg.counter(
            "ptpu_cluster_respawns_total",
            "dead workers the supervisor respawned")
        self._m_kills = reg.counter(
            "ptpu_cluster_worker_kills_total",
            "worker processes reaped, by how they died",
            labels=("kind",))

    # -- process lifecycle ---------------------------------------------
    def _spawn_process(self, slot: WorkerHandle) -> None:
        import paddle_tpu
        slot.generation += 1
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(paddle_tpu.__file__)))
        env = os.environ.copy()
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        # the secret travels through the child environment only: argv
        # is world-readable (/proc), the store is what it authenticates
        env["PTPU_CLUSTER_SECRET"] = self._secret.decode(
            "utf-8", "surrogateescape")
        slot.proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving.worker",
             "--store-host", self.advertise_host,
             "--store-port", str(self._store.port),
             "--prefix", self._prefix,
             "--worker-id", slot.wid,
             "--bind-host", self.bind_host,
             "--advertise-host", self.advertise_host],
            env=env, cwd=root)

    def _await_ready(self, slot: WorkerHandle) -> None:
        key = f"{self._prefix}/{slot.wid}/port"
        deadline = time.monotonic() + self._spawn_timeout
        while True:
            try:
                self._store.wait(key, timeout=2.0)
                break
            except Exception:
                if slot.proc.poll() is not None:
                    raise RuntimeError(
                        f"cluster worker {slot.wid} exited with "
                        f"{slot.proc.returncode} before publishing "
                        f"its port")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"cluster worker {slot.wid} not ready within "
                        f"{self._spawn_timeout}s")

        def opened(k: str) -> bytes:
            # rendezvous values ride sealed envelopes: a tampered
            # port/pid/host is a typed AuthError, not a wrong dial
            return open_sealed(self._secret, k, self._store.get(k))

        slot.port = int(opened(key))
        slot.pid = int(opened(f"{self._prefix}/{slot.wid}/pid"))
        slot.host = opened(
            f"{self._prefix}/{slot.wid}/host").decode("utf-8")
        self._m_alive.labels(worker=slot.slot_label).set(1)

    def _make_client(self, slot: WorkerHandle) -> RemoteEngine:
        if slot.client is not None:
            slot.client.close()   # single-connection worker: the old
            #                       socket must die before the new
            #                       client can be heard (serve loop)
        client = RemoteEngine(
            slot.host, slot.port, name=slot.slot_label,
            engine_kw=self._episode["engine"], time_fn=self._time_fn,
            registry=self.registry, proc=slot.proc,
            secret=self._secret, **self._client_kwargs)
        client.worker_pid = slot.pid
        slot.client = client
        return client

    def start(self) -> ReplicaRouter:
        """Spawn ``n_workers`` processes and build the router."""
        from ..distributed.store import TCPStore
        if self._store is not None:
            raise RuntimeError("ClusterSupervisor already started")
        self._store = TCPStore(self.bind_host, 0, is_master=True,
                               world_size=1)
        if self._weight_store_dir:
            self._publish_weights()
        key = f"{self._prefix}/spec"
        # sealed so a tampered spec fails its MAC before the worker's
        # restricted unpickler even runs (defense in depth)
        self._store.set(key, seal(self._secret, key,
                                  pickle.dumps(self.spec)))
        self._slots = [WorkerHandle(i) for i in range(self.n_workers)]
        for slot in self._slots:          # spawn all, then wait all:
            self._spawn_process(slot)     # startups overlap
        for slot in self._slots:
            self._await_ready(slot)
        return self._build_router()

    def _publish_weights(self) -> None:
        """Build the model ONCE supervisor-side and publish its state
        dict into the content-addressed store; the spec then carries
        nothing but the store root and the manifest digest — workers
        fetch and sha256-verify every chunk (worker.py
        ``_apply_published_weights``), so a corrupt store is a typed
        retryable failure, never silently wrong weights."""
        from .weight_store import WeightStore
        from .worker import WorkerServer
        ws = WeightStore(self._weight_store_dir,
                         registry=self.registry)
        model = WorkerServer._build_model(self.spec)
        digest = ws.publish(model.state_dict())
        self.spec["weights"] = {"dir": ws.root, "manifest": digest}

    def _build_router(self) -> ReplicaRouter:
        replicas = [RemoteReplica(str(slot.index),
                                  self._make_client(slot), slot)
                    for slot in self._slots]
        for slot, rep in zip(self._slots, replicas):
            slot.replica = rep
            slot.reaped = False
        self.router = ReplicaRouter(
            replicas, registry=self.registry,
            flight_recorder=self.recorder, auditor=self.auditor,
            **self._router_kwargs)
        return self.router

    def new_episode(self, engine_kw: Optional[Dict[str, Any]] = None,
                    *, donate: bool = False,
                    virtual_clock: Optional[bool] = None,
                    time_fn: Optional[Callable[[], float]] = None,
                    auditor=None) -> ReplicaRouter:
        """Re-arm the cluster over the WARM worker processes: fresh
        engines (one ``reset`` RPC each; a process that died since the
        last episode is respawned, budget-free), fresh clients, fresh
        router, respawn budget restored."""
        if self._store is None:
            raise RuntimeError("start() the supervisor first")
        self._episode = {
            "engine": dict(engine_kw if engine_kw is not None
                           else self.spec.get("engine") or {}),
            "donate": bool(donate),
            "virtual_clock": bool(
                self._episode["virtual_clock"]
                if virtual_clock is None else virtual_clock)}
        if time_fn is not None:
            self._time_fn = time_fn
        if auditor is not None:
            self.auditor = auditor
        self.respawns_used = 0
        self._polls = 0
        if self.telemetry is not None:
            # the tier-1 suite runs many supervisors in ONE process:
            # re-claim the global buffer in case a later supervisor
            # installed its own, and start the episode's merge clean
            install_trace_buffer(self._host_buffer)
            self._host_buffer.drain()       # stale pre-episode spans
            self.telemetry.begin_episode()
        for slot in self._slots:
            if not self._reset_slot(slot):
                self._hard_respawn(slot)
        return self._build_router()

    def _reset_slot(self, slot: WorkerHandle) -> bool:
        if not slot.alive():
            return False
        try:
            client = self._make_client(slot)
            client.reset(self._episode["engine"],
                         donate=self._episode["donate"],
                         virtual_clock=self._episode["virtual_clock"])
            if self.telemetry is not None and slot.pid is not None:
                # reset installs a FRESH worker trace buffer (counters
                # restart at 0) — rebaseline so the next scrape isn't
                # mistaken for a replayed blob
                self.telemetry.rebaseline(slot.slot_label, slot.pid)
            return True
        except Exception:
            return False

    def _hard_respawn(self, slot: WorkerHandle) -> None:
        if slot.alive():
            slot.proc.kill()
            slot.proc.wait()
        self._spawn_process(slot)
        self._await_ready(slot)
        if not self._reset_slot(slot):
            raise RuntimeError(
                f"cluster worker {slot.wid} respawned but failed "
                f"its engine reset")

    # -- reap + respawn -------------------------------------------------
    def poll(self) -> None:
        """Reap every replica the router declared DEAD: fence its
        process (SIGKILL if still running — a partitioned worker must
        not keep computing), record the death (flight-recorder dump
        carries the post-mortem), and — with ``respawn`` — bring a
        fresh replica up and re-register it, bounded by
        ``max_respawns`` → typed :class:`RestartLimitExceeded`."""
        if self.router is None:
            return
        for slot in self._slots:
            rep = slot.replica
            if rep is None or rep.state != DEAD or slot.reaped:
                continue
            self._reap(slot)
        if self.telemetry is not None and self.scrape_interval > 0:
            self._polls += 1
            if self._polls % self.scrape_interval == 0:
                self.scrape_all()

    # -- telemetry scrape -----------------------------------------------
    def scrape_all(self) -> None:
        """One telemetry sweep: scrape every live worker's trace
        buffer + registry snapshot into the merger, then drain the
        host-side buffer (router/dispatch spans). A scrape that cannot
        reach its worker is recorded as a LOSS in the merger — a
        truncated timeline must be detectable, not silent."""
        tel = self.telemetry
        if tel is None:
            return
        for slot in self._slots:
            client, rep = slot.client, slot.replica
            if client is None or client._dead \
                    or rep is None or not rep.live:
                continue
            try:
                payload = client.telemetry()
            except Exception:
                tel.forget(slot.slot_label,
                           client.worker_pid or slot.pid or 0)
                continue
            tel.ingest_worker(slot.slot_label, payload,
                              host_now=self._time_fn())
        if self._host_buffer is not None:
            tel.ingest_host(self._host_buffer.drain(), proc="router")

    def _death_scrape(self, slot: WorkerHandle) -> None:
        """Last-chance scrape of a dead REPLICA whose process still
        runs (cooperative kill, client-side partition): the old client
        is done for, so a short-deadline fresh connection pulls the
        final spans before the slot is fenced/reset."""
        tel = self.telemetry
        try:
            if slot.client is not None:
                slot.client.close()   # single-connection worker
            tmp = RemoteEngine(
                slot.host, slot.port, name=slot.slot_label,
                engine_kw=self._episode["engine"],
                time_fn=self._time_fn, registry=self.registry,
                proc=slot.proc, call_deadline_s=5.0,
                secret=self._secret)
            try:
                payload = tmp.telemetry()
                tel.ingest_worker(slot.slot_label, payload,
                                  host_now=self._time_fn())
            finally:
                tmp.close()
        except Exception:
            tel.forget(slot.slot_label, slot.pid or 0,
                       reason="death_scrape_failed")

    def _load_victim_flight(self, slot: WorkerHandle) -> Optional[dict]:
        """The dead worker's last flight-recorder spill, if any."""
        if slot.pid is None:
            return None
        path = os.path.join(str(self.spec.get("spill_dir")
                                or self._spill_dir),
                            f"flight_{slot.pid}.json")
        try:
            with open(path) as f:
                return json.load(f)
        except Exception:
            return None

    def _reap(self, slot: WorkerHandle) -> None:
        slot.reaped = True
        exited = not slot.alive()
        self._m_alive.labels(worker=slot.slot_label).set(0)
        self.recorder.record(
            "cluster.worker_dead", worker=slot.wid,
            replica=slot.replica.id if slot.replica else None,
            exited=exited,
            returncode=slot.proc.returncode if exited else None)
        if self.telemetry is not None:
            if exited:
                # SIGKILL/crash: the un-scraped tail of its trace
                # buffer died with the process — record the loss
                self.telemetry.forget(slot.slot_label, slot.pid or 0,
                                      reason="worker_died")
            else:
                self._death_scrape(slot)
        if self._dump_on_death:
            try:
                victim = self._load_victim_flight(slot)
                self.recorder.dump(
                    reason=f"cluster worker {slot.wid} dead",
                    registry=self.registry,
                    extra={"victim_flight": victim}
                    if victim is not None else None)
            except Exception:
                pass
        if self.router is None or getattr(self.router, "_closed",
                                          False):
            # the router already drained (episode over): there is
            # nobody to re-register a fresh replica with, and nothing
            # in flight to recover — fence the process and leave the
            # slot dead; the next new_episode() respawns it
            # budget-free.
            if not exited:
                slot.proc.kill()
                slot.proc.wait()
            self._m_kills.labels(
                kind="exited" if exited else "sigkill").inc()
            return
        soft = False
        if not self.respawn or self.respawns_used >= self.max_respawns:
            # fence even when not respawning: the orphaned process
            # must not keep decoding into pools nobody reads
            if not exited:
                slot.proc.kill()
                slot.proc.wait()
            self._m_kills.labels(
                kind="exited" if exited else "sigkill").inc()
            if self.respawn:
                raise RestartLimitExceeded(
                    f"cluster supervisor: worker {slot.wid} died but "
                    f"the respawn budget is exhausted "
                    f"({self.respawns_used} used, max_respawns="
                    f"{self.max_respawns})")
            return
        if not exited:
            # warm process behind a dead *replica* (cooperative kill,
            # exhausted partition): reclaim it with a reset — same
            # fencing effect (all engine state discarded), no spawn
            soft = self._reset_slot(slot)
            if not soft:
                slot.proc.kill()
                slot.proc.wait()
                self._m_kills.labels(kind="sigkill").inc()
        if exited:
            self._m_kills.labels(kind="exited").inc()
        if not soft:
            self._spawn_process(slot)
            self._await_ready(slot)
            if not self._reset_slot(slot):
                raise RuntimeError(
                    f"cluster worker {slot.wid} respawned but failed "
                    f"its engine reset")
        self.respawns_used += 1
        slot.respawns += 1
        self._m_respawns.inc()
        self._m_worker_respawns.labels(
            worker=slot.slot_label).set(slot.respawns)
        self._m_alive.labels(worker=slot.slot_label).set(1)
        new_id = f"{slot.index}r{slot.respawns}"
        rep = RemoteReplica(new_id, slot.client, slot)
        self.router.add_replica(rep)
        slot.replica = rep
        slot.reaped = False
        self.recorder.record("cluster.worker_respawned",
                             worker=slot.wid, replica=new_id,
                             soft=soft)

    # -- autoscaling ----------------------------------------------------
    def scale_up(self) -> RemoteReplica:
        """Hot capacity add (the control plane's autoscaler): spawn a
        fresh worker process, wait for ready + engine reset, register
        it with the RUNNING router. The new slot is a first-class
        worker afterwards — polled, reaped, respawnable."""
        if self._store is None or self.router is None:
            raise RuntimeError("start() the supervisor first")
        slot = WorkerHandle(len(self._slots))
        self._slots.append(slot)
        self._spawn_process(slot)
        self._await_ready(slot)
        if not self._reset_slot(slot):
            raise RuntimeError(
                f"cluster worker {slot.wid} spawned for scale-up but "
                f"failed its engine reset")
        rep = RemoteReplica(f"s{slot.index}", slot.client, slot)
        self.router.add_replica(rep)
        slot.replica = rep
        slot.reaped = False
        self._m_alive.labels(worker=slot.slot_label).set(1)
        self.recorder.record("cluster.worker_scaled_up",
                             worker=slot.wid, replica=rep.id)
        return rep

    def scale_down(self, replica_id: Optional[str] = None) \
            -> Optional[str]:
        """Shrink by one worker: ``drain_replica`` re-homes its queued
        work to peers, then the process is shut down once its engine
        is empty (else it keeps draining and a later call — or
        ``poll()`` on death — finishes the job). Never drains the last
        dispatchable worker. Returns the drained replica id or None."""
        if self.router is None:
            raise RuntimeError("start() the supervisor first")
        live = [s for s in self._slots
                if s.replica is not None and s.replica.dispatchable]
        if replica_id is None:
            cands = live
        else:
            cands = [s for s in live if s.replica.id == replica_id]
        if len(live) <= 1 or not cands:
            return None
        slot = cands[-1]
        rid = slot.replica.id
        self.router.drain_replica(rid)
        try:
            drained = not slot.replica.engine.has_work()
        except Exception:
            drained = True
        if drained and slot.alive():
            try:
                slot.client.shutdown()
            except Exception:
                pass
            if slot.proc.poll() is None:
                slot.proc.kill()
                try:
                    slot.proc.wait(timeout=10.0)
                except Exception:
                    pass
            slot.reaped = True
            self._m_alive.labels(worker=slot.slot_label).set(0)
        self.recorder.record("cluster.worker_scaled_down",
                             worker=slot.wid, replica=rid,
                             drained=drained)
        return rid

    # -- teardown -------------------------------------------------------
    def shutdown(self) -> None:
        for slot in self._slots:
            if slot.client is not None and slot.alive():
                slot.client.shutdown()
            if slot.proc is not None:
                if slot.proc.poll() is None:
                    slot.proc.kill()
                try:
                    slot.proc.wait(timeout=10.0)
                except Exception:
                    pass
            self._m_alive.labels(worker=slot.slot_label).set(0)
        if self._store is not None:
            try:
                self._store.close()
            except Exception:
                pass
            self._store = None

    # -- introspection --------------------------------------------------
    @property
    def workers(self) -> List[WorkerHandle]:
        return list(self._slots)
