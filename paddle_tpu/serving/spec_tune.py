"""Accept-rate autotuning for speculative decoding.

Speculation only pays when drafts get accepted: a k-wide verify step
costs ~k times the FLOPs of a k=1 decode step (same weights pass, k
token positions), so at accepted length a the speedup is ~a per step
— below a ≈ 1 + overhead it's a pure loss, and the draft model adds
its own forward cost on top. Which proposer wins (n-gram lookup vs a
small draft model) and which window k pays is a property of the
TRAFFIC, not the config: templated traffic drafts well from n-grams,
novel prose only from a draft model, adversarial prompts from
neither. The tuner closes the loop the observability layer already
opened: it feeds per-request-class EWMAs of the accepted-length
histogram (PR 17's `ptpu_serving_spec_accepted_length`) back into a
per-step (k, proposer) decision.

Hysteresis is the point, not a refinement. The engine compiles ONE
k-wide verify program and ONE k=1 decode program; the tuner only ever
routes between them (its k caps the DRAFT length inside the same
verify program — a row drafting d tokens runs wlen=d+1), so there is
no compile cost to a flip — but accepted length measured while OFF is
stale, so the tuner would otherwise flap: turn off, forget, probe,
turn on, measure one bad step, turn off. Dwell-gated thresholds with
a deterministic probe cadence (every ``probe_every`` steps while off,
one k=2 probe step, round-robin over proposers) keep decisions
piecewise-constant and replayable — no RNG, no clock, pure counters,
so chaos episodes with a tuner stay bit-identical per seed.

Decisions surface as ``ptpu_spec_tuner_k{klass}`` gauges and
``ptpu_spec_proposer_total{kind}`` counters (the engine exports both)
and in ``ptpu_doctor``'s speculation line.
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Sequence, Tuple

__all__ = ["SpecTuner"]

# request classes tuned independently: greedy acceptance is exact
# token match (brittle, often long runs), sampled acceptance is
# probabilistic min(1, p/q) (smoother, usually shorter runs) — one
# EWMA would average two different regimes into tuning neither
CLASSES = ("greedy", "sampled")


class SpecTuner:
    """Per-request-class (k, proposer) controller over accepted-length
    EWMAs. ``decide(klass)`` is read per row per step; ``observe``
    feeds verified accepted lengths back; ``on_step`` advances the
    clock and applies the dwell-gated transitions.

    Knobs (all deterministic):

    - ``k_max``: ceiling for the tuned k (the engine's compiled
      ``spec_k``; the tuner never exceeds the program window).
    - ``alpha``: EWMA smoothing for accepted length.
    - ``enable_at`` / ``disable_at``: accepted-length thresholds for
      turning speculation on / off, split apart so the controller has
      a dead band instead of a flap line.
    - ``dwell``: minimum steps between state flips for one class.
    - ``probe_every``: while off, run one k=2 probe step at this
      cadence (round-robin over proposers) so the EWMA can recover
      when traffic turns draftable again.
    - ``switch_margin``: a rival proposer must beat the incumbent's
      EWMA by this much before the tuner switches kinds.
    """

    def __init__(self, k_max: int,
                 proposers: Sequence[str] = ("ngram",),
                 alpha: float = 0.25,
                 enable_at: float = 1.35,
                 disable_at: float = 1.15,
                 dwell: int = 8,
                 probe_every: int = 32,
                 switch_margin: float = 0.25):
        if k_max < 2:
            raise ValueError(f"k_max must be >= 2, got {k_max}")
        if not proposers:
            raise ValueError("at least one proposer kind required")
        if not 0 < alpha <= 1:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if disable_at > enable_at:
            raise ValueError(
                f"disable_at={disable_at} must not exceed "
                f"enable_at={enable_at} (the dead band)")
        self.k_max = int(k_max)
        self.proposers = tuple(proposers)
        self.alpha = float(alpha)
        self.enable_at = float(enable_at)
        self.disable_at = float(disable_at)
        self.dwell = int(dwell)
        self.probe_every = int(probe_every)
        self.switch_margin = float(switch_margin)
        self._step = 0
        # (klass, kind) -> EWMA accepted length (None until seen)
        self._ewma: Dict[Tuple[str, str], Optional[float]] = {
            (c, p): None for c in CLASSES for p in self.proposers}
        # optimistic start: speculate from step 0 with the first
        # proposer at full k — the EWMA then earns (or loses) it
        self._st = {c: {"on": True, "k": self.k_max,
                        "kind": self.proposers[0], "since": 0,
                        "probe_i": 0}
                    for c in CLASSES}
        self.flips = 0                      # state transitions (tests)

    # -- per-row read --------------------------------------------------
    def decide(self, klass: str) -> Tuple[int, Optional[str]]:
        """(k, proposer kind) for a row of this class THIS step; kind
        None means don't draft (the row runs wlen=1 — and when every
        row says so, the engine's spec_gate routes the whole step onto
        the cheap k=1 decode program)."""
        st = self._st[klass]
        if st["on"]:
            return st["k"], st["kind"]
        if self.probe_every > 0 \
                and self._step % self.probe_every == 0:
            kind = self.proposers[st["probe_i"] % len(self.proposers)]
            return 2, kind
        return 1, None

    # -- feedback ------------------------------------------------------
    def observe(self, klass: str, kind: str, accepted: int) -> None:
        """Feed one verified row's accepted length (1 = only the base
        token, i.e. every draft rejected)."""
        key = (klass, kind)
        prev = self._ewma.get(key)
        x = float(accepted)
        self._ewma[key] = x if prev is None \
            else prev + self.alpha * (x - prev)

    def on_step(self) -> None:
        """Advance the step clock and apply dwell-gated transitions."""
        # rotate the probe cursor when a probe step just ran, so the
        # next probe exercises the other proposer
        for c in CLASSES:
            st = self._st[c]
            if not st["on"] and self.probe_every > 0 \
                    and self._step % self.probe_every == 0:
                st["probe_i"] += 1
        self._step += 1
        for c in CLASSES:
            self._evaluate(c)

    def _evaluate(self, klass: str) -> None:
        st = self._st[klass]
        if self._step - st["since"] < self.dwell:
            return
        seen = [(kind, self._ewma[(klass, kind)])
                for kind in self.proposers
                if self._ewma[(klass, kind)] is not None]
        if not seen:
            return
        best_kind, best = max(seen, key=lambda kv: kv[1])
        if st["on"]:
            cur = self._ewma.get((klass, st["kind"]))
            if cur is not None and cur < self.disable_at \
                    and best < self.enable_at:
                st.update(on=False, since=self._step)
                self.flips += 1
                return
            if best_kind != st["kind"] and cur is not None \
                    and best > cur + self.switch_margin:
                st.update(kind=best_kind, since=self._step)
                self.flips += 1
            k = min(self.k_max, max(2, int(math.ceil(
                self._ewma[(klass, st["kind"])] or 2)) + 1))
            st["k"] = k
        elif best > self.enable_at:
            k = min(self.k_max, max(2, int(math.ceil(best)) + 1))
            st.update(on=True, kind=best_kind, k=k,
                      since=self._step)
            self.flips += 1

    # -- readout -------------------------------------------------------
    def snapshot(self) -> dict:
        """Deterministic state dump for gauges, the watchtower JSON
        and ``ptpu_doctor``."""
        return {
            "step": self._step,
            "flips": self.flips,
            "classes": {
                c: {"on": self._st[c]["on"],
                    "k": self._st[c]["k"] if self._st[c]["on"] else 1,
                    "kind": self._st[c]["kind"]
                    if self._st[c]["on"] else None,
                    "ewma": {p: self._ewma[(c, p)]
                             for p in self.proposers}}
                for c in CLASSES},
        }
