"""Serving-cluster worker: one ServingEngine behind a framed RPC loop.

``python -m paddle_tpu.serving.worker`` is the entrypoint
:class:`~paddle_tpu.serving.cluster.ClusterSupervisor` spawns — one
process per replica. Rendezvous rides the native TCPStore: the
supervisor publishes a pickled *spec* (model config + engine kwargs)
under ``<prefix>/spec``; the worker builds the model, binds an
ephemeral TCP port, publishes it under ``<prefix>/<worker-id>/port``
(pid alongside, so the supervisor can SIGKILL a partitioned worker),
and serves framed request/response RPC forever.

Protocol (one pickled dict per ``_framing`` frame). With a cluster
secret (``PTPU_CLUSTER_SECRET``, always set by the supervisor) every
accepted connection must pass the shared-secret handshake before its
first frame is parsed, and every frame carries a sequenced MAC — an
unauthenticated or tampered peer is a counted typed rejection
(``AuthError``) and the serve loop simply waits for the next
connection; the worker never crashes and never unpickles bytes that
failed authentication. The spec itself arrives sealed and is
unpickled under ``_framing.restricted_loads``'s data-only allowlist.

- every request carries ``(token, seq)``; the worker caches its last
  response per token so a client that lost a response to a partition
  can reconnect and *resend* without the operation running twice —
  the exactly-once property the router's delivery gate needs holds
  across retries, not just clean calls.
- ``step``/``drain``/``recover`` responses carry the rids the
  operation *returned* (the router delivers exactly those) plus a
  full per-rid state refresh (tokens so far, finish reason, error)
  and an engine summary (queue order, slot map, undelivered debt) —
  the client mirrors it so the router's failover can re-home
  everything from host-side state when this process dies.
- ``reset`` swaps in a fresh engine (and clears armed faults), so a
  chaos band reuses warm worker processes across episodes instead of
  paying a process spawn per seed.
- ``arm`` arms a resilience fault point in THIS process; with
  ``kill=True`` the "exception" is ``os.kill(getpid(), SIGKILL)`` —
  the mid-step hard-death kind the failover certification needs.
- ``stall`` delays every subsequent response: the hung-worker case a
  probe timeout must classify as SUSPECT, not DEAD.

Engine clock: with ``spec["virtual_clock"]`` the engine's ``time_fn``
returns the last ``now`` any RPC carried — the chaos episodes' virtual
clock spans the process boundary, so deadline laws stay deterministic.
"""
from __future__ import annotations

import argparse
import os
import pickle
import signal
import socket
import time
from typing import Any, Dict, List, Optional

__all__ = ["main", "WorkerServer"]


def _wire_error(e: BaseException) -> BaseException:
    """Best-effort typed error across the pickle boundary."""
    from .errors import RemoteError, ServingError
    if isinstance(e, ServingError):
        return e
    try:
        pickle.loads(pickle.dumps(e))
        return e
    except Exception:
        return RemoteError(type(e).__name__, str(e))


class WorkerServer:
    """The in-process half: owns the engine, dispatches ops."""

    def __init__(self, spec: Dict[str, Any], worker_id: str,
                 secret: Optional[bytes] = None):
        self.spec = spec
        self.worker_id = worker_id
        self._secret = secret
        self._clock = {"t": 0.0}
        self._virtual = bool(spec.get("virtual_clock"))
        self._stall_s = 0.0
        # (token, seq) -> response blob: resend-dedup (see module doc)
        self._last_key: Optional[tuple] = None
        self._last_blob: Optional[bytes] = None
        self._model = self._build_model(spec)
        self._apply_published_weights()
        self.engine = None
        self._reqs: Dict[int, Any] = {}
        self._trace_buf = None
        self._make_engine(spec.get("engine") or {},
                          donate=bool(spec.get("donate")))

    # -- construction --------------------------------------------------
    @staticmethod
    def _build_model(spec: Dict[str, Any]):
        import paddle_tpu as paddle
        from ..models.llama import (LlamaConfig, LlamaForCausalLM,
                                    llama_tiny_config)
        paddle.seed(int(spec.get("model_seed", 0)))
        kw = dict(spec.get("model_config") or {})
        cfg = llama_tiny_config(**kw) if spec.get("tiny", True) \
            else LlamaConfig(**kw)
        model = LlamaForCausalLM(cfg)
        model.eval()
        return model

    def _apply_published_weights(self) -> None:
        """Load parameters from the shared weight store when the spec
        carries a manifest digest. Every chunk is sha256-verified; a
        corrupt or short read is a typed retryable failure and the
        worker dies loudly rather than serve silently wrong weights."""
        w = self.spec.get("weights")
        if not w:
            return               # legacy path: seed-built weights stand
        from .weight_store import WeightStore, WeightStoreError
        state = WeightStore(w["dir"]).fetch(w["manifest"])
        missing, unexpected = self._model.set_state_dict(state)
        if missing or unexpected:
            raise WeightStoreError(
                f"published manifest does not cover the model: "
                f"missing={missing!r} unexpected={unexpected!r}")

    def _now(self) -> float:
        return self._clock["t"] if self._virtual else time.monotonic()

    def _make_engine(self, engine_kw: Dict[str, Any],
                     donate: bool = False) -> None:
        from ..distributed._framing import register_auth_failure_hook
        from ..observability import (FlightRecorder, MetricRegistry,
                                     TraceBuffer, clear_bindings,
                                     install_trace_buffer)
        from ..resilience import faults
        from .engine import ServingEngine
        faults.clear()           # episode hygiene: no armed leftovers
        clear_bindings()
        registry = MetricRegistry()
        # server-side rejections (unauthenticated clients, garbage
        # MACs) land on the worker's registry and merge through the
        # ordinary telemetry scrape
        self._m_auth = registry.counter(
            "ptpu_cluster_auth_failures_total",
            "typed auth rejections: failed handshakes, bad/replayed "
            "frame MACs, tampered rendezvous values, disallowed spec "
            "globals")
        register_auth_failure_hook(self._on_auth_failure)
        # fresh buffer per engine incarnation: counters restart at 0,
        # which the host-side merger treats as a rebaseline (the
        # supervisor calls telemetry.rebaseline after each reset)
        self._trace_buf = TraceBuffer(
            capacity=int(self.spec.get("trace_capacity", 2048)),
            time_fn=self._now)
        install_trace_buffer(self._trace_buf)
        # flight ring spills to <spill_dir>/flight_<pid>.json every
        # spill_every records (and on SIGTERM), so even a SIGKILLed
        # worker leaves its last records for the supervisor's death
        # dump to attach
        spill_dir = self.spec.get("spill_dir")
        spill_path = os.path.join(
            str(spill_dir), f"flight_{os.getpid()}.json") \
            if spill_dir else None
        self.engine = ServingEngine(
            self._model, time_fn=self._now,
            registry=registry,
            flight_recorder=FlightRecorder(
                capacity=64, time_fn=self._now,
                spill_path=spill_path,
                spill_every=int(self.spec.get("spill_every", 8))),
            **engine_kw)
        if donate:
            # chaos: a step failure invalidates the cache pools, so
            # recover()/failover paths are exercised for real
            self.engine._donate = lambda: (5, 6)
        self._reqs = {}

    def _on_auth_failure(self, _reason: str) -> None:
        m = getattr(self, "_m_auth", None)
        if m is not None:
            try:
                m.inc()
            except Exception:
                pass            # a metrics hiccup must not mask the rejection

    # -- response plumbing ---------------------------------------------
    def _state(self) -> Dict[str, Any]:
        eng = self.engine
        return {
            "queued": [r.rid for r in eng.scheduler.pending()],
            "slots": {int(s): eng.cache.slots[s].rid
                      for s in eng.cache.active_slots()},
            "undelivered": [r.rid for r in eng._undelivered],
            "broken": eng._broken,
        }

    def _updates(self, extra: Optional[List] = None) -> Dict[int, dict]:
        ups: Dict[int, dict] = {}
        for req in list(self._reqs.values()) + list(extra or []):
            ups[req.rid] = {
                "out": list(req.out_tokens),
                "finished": bool(req.finished),
                "reason": req.finish_reason,
                "error": _wire_error(req.error)
                if req.error is not None else None,
                "slot": req.slot,
            }
        return ups

    def _ok(self, finished: Optional[List] = None, **extra) -> dict:
        done = finished or []
        resp = {"ok": True, "finished": [r.rid for r in done],
                "updates": self._updates(done),
                "state": self._state()}
        resp.update(extra)
        self._prune()
        return resp

    def _err(self, e: BaseException) -> dict:
        resp = {"ok": False, "error": _wire_error(e),
                "updates": self._updates(), "state": self._state()}
        self._prune()
        return resp

    def _prune(self) -> None:
        # terminal requests were reported (and the blob is cached for
        # a resend) — drop them so updates stay O(in-flight); their
        # trace bindings go with them (bounded binding table)
        from ..observability import unbind_request
        for rid, r in self._reqs.items():
            if r.finished:
                unbind_request(rid)
        self._reqs = {rid: r for rid, r in self._reqs.items()
                      if not r.finished}

    @staticmethod
    def _bind_trace(req) -> None:
        # the router minted req.trace before the dispatch RPC; bind
        # rid → context so engine spans (which only carry request_id)
        # join the request's distributed trace
        from ..observability import bind_request
        bind_request(req.rid, getattr(req, "trace", None))

    def _mark_cancels(self, msg: dict) -> None:
        # the client's FrontDoor flags disconnects on ITS Request
        # objects; forward the flags so the engine's own sweep runs
        # the real mid-prefill/mid-handoff abort paths
        for rid in msg.get("cancel_rids") or ():
            req = self._reqs.get(rid)
            if req is not None:
                req.cancel_requested = True

    # -- ops -----------------------------------------------------------
    def dispatch(self, msg: dict) -> dict:
        if "now" in msg and msg["now"] is not None:
            self._clock["t"] = float(msg["now"])
        op = msg["op"]
        eng = self.engine
        try:
            if op == "probe":
                from ..distributed._framing import auth_failures
                health = eng.probe()
                # process-wide rejection count: the unauth-client test
                # asserts it through an AUTHENTICATED probe
                health["auth_failures"] = auth_failures()
                return self._ok(pid=os.getpid(), health=health)
            if op == "submit":
                req = msg["req"]
                self._bind_trace(req)
                eng.submit_request(req)
                self._reqs[req.rid] = req
                return self._ok()
            if op == "adopt":
                req = msg["req"]
                self._bind_trace(req)
                eng.adopt(req)
                self._reqs[req.rid] = req
                return self._ok()
            if op == "step":
                self._mark_cancels(msg)
                if not eng.has_work():
                    return self._ok()
                return self._ok(finished=eng.step())
            if op == "recover":
                report = eng.recover()
                return self._ok(finished=report["finished"])
            if op == "drain":
                self._mark_cancels(msg)
                return self._ok(finished=eng.drain(msg.get("max_steps")))
            if op == "cancel":
                req = self._reqs.get(msg["rid"])
                hit = req is not None and \
                    eng.cancel(req, msg.get("reason", "cancelled"))
                return self._ok(finished=[req] if hit else None,
                                cancelled=bool(hit))
            if op == "unqueue":
                # drain_replica: queued requests move to peers NOW
                from ..observability import unbind_request
                moved = eng.scheduler.drain()
                for r in moved:
                    self._reqs.pop(r.rid, None)
                    unbind_request(r.rid)
                return self._ok(moved=[r.rid for r in moved])
            if op == "requeue":
                req = msg["req"]
                self._bind_trace(req)
                eng.scheduler.requeue(req)
                self._reqs[req.rid] = req
                return self._ok()
            if op == "telemetry":
                buf = self._trace_buf
                payload = {
                    "pid": os.getpid(), "now": self._now(),
                    "spans": buf.drain() if buf is not None else [],
                    "drained_total":
                        buf.drained_total if buf is not None else 0,
                    "dropped_total":
                        buf.dropped_total if buf is not None else 0,
                    "recorded_total":
                        buf.recorded_total if buf is not None else 0,
                    "registry": eng.registry.to_json()}
                return self._ok(telemetry=payload)
            if op == "audit":
                from ..resilience.invariants import (
                    engine_leak_violations, page_leak_violations)
                v = engine_leak_violations(eng) \
                    + page_leak_violations(eng)
                return self._ok(violations=v,
                                trace_counts=eng.trace_counts)
            if op == "reset":
                # re-verify the published weights BEFORE _make_engine
                # clears armed faults, so a chaos arm on
                # cluster.weights.fetch lands on this exact fetch; a
                # failure past the retry budget is a typed refusal and
                # the supervisor hard-respawns instead of soft-reclaim
                self._apply_published_weights()
                self._make_engine(msg.get("engine") or {},
                                  donate=bool(msg.get("donate")))
                self._virtual = bool(msg.get("virtual_clock",
                                             self._virtual))
                self._stall_s = 0.0
                return self._ok()
            if op == "stall":
                self._stall_s = float(msg.get("seconds", 0.0))
                return self._ok()
            if op == "arm":
                from ..resilience import faults
                if msg.get("kill"):
                    def _suicide(*_a, **_k):
                        os.kill(os.getpid(), signal.SIGKILL)
                    exc = _suicide
                else:
                    exc = None
                faults.inject(msg["point"],
                              times=msg.get("times", 1),
                              after=msg.get("after", 0), exc=exc)
                return self._ok()
            raise ValueError(f"unknown worker op {op!r}")
        except Exception as e:  # typed refusal, not a dead worker
            return self._err(e)

    # -- the serve loop ------------------------------------------------
    def serve(self, srv: socket.socket) -> None:
        from ..distributed._framing import (nodelay, recv_msg,
                                            send_msg, server_handshake)
        while True:
            conn, _ = srv.accept()
            nodelay(conn)
            auth = None
            try:
                if self._secret is not None:
                    # a peer that cannot pass the handshake — an
                    # unauthenticated client, a wrong secret, garbage
                    # bytes — raises a counted typed AuthError here
                    # (a ConnectionError): this connection dies, the
                    # loop accepts the next one, no frame of it was
                    # ever unpickled
                    auth = server_handshake(conn, self._secret)
                while True:
                    blob = recv_msg(conn, eof_ok=True, auth=auth)
                    if blob is None:
                        break
                    msg = pickle.loads(blob)
                    key = (msg.get("token"), msg.get("seq"))
                    stall = self._stall_s
                    if key == self._last_key \
                            and self._last_blob is not None:
                        out = self._last_blob   # resend, don't re-run
                    elif msg.get("op") == "shutdown":
                        send_msg(conn, pickle.dumps(
                            {"ok": True, "seq": msg.get("seq")}),
                            auth=auth)
                        os._exit(0)
                    else:
                        resp = self.dispatch(msg)
                        resp["seq"] = msg.get("seq")
                        try:
                            out = pickle.dumps(resp)
                        except Exception as e:
                            out = pickle.dumps(
                                {"ok": False, "seq": msg.get("seq"),
                                 "error": _wire_error(e)})
                        self._last_key, self._last_blob = key, out
                    if stall:
                        time.sleep(stall)
                    send_msg(conn, out, auth=auth)
            except (ConnectionError, OSError):
                pass             # client gone/rejected; await the next
            finally:
                try:
                    conn.close()
                except OSError:
                    pass


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="paddle_tpu serving-cluster worker")
    parser.add_argument("--store-host", default="127.0.0.1")
    parser.add_argument("--store-port", type=int, required=True)
    parser.add_argument("--prefix", required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--bind-host", default="127.0.0.1",
                        help="local interface the RPC server binds")
    parser.add_argument("--advertise-host", default=None,
                        help="address published for peers to dial "
                             "(defaults to --bind-host)")
    args = parser.parse_args(argv)
    advertise = args.advertise_host or args.bind_host
    # the supervisor always exports the cluster secret into this
    # process's environment; absent = legacy unauthenticated framing
    secret_env = os.environ.get("PTPU_CLUSTER_SECRET", "")
    secret = secret_env.encode("utf-8", "surrogateescape") \
        if secret_env else None

    # the TPU plugin force-sets jax_platforms at interpreter startup;
    # honor the env the supervisor handed us (tests/benches force cpu)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    from ..distributed._framing import open_sealed, restricted_loads
    from ..distributed.store import TCPStore
    store = TCPStore(args.store_host, args.store_port,
                     is_master=False, world_size=1)
    spec_key = f"{args.prefix}/spec"
    blob = store.get(spec_key, timeout=60.0)
    if secret is not None:
        blob = open_sealed(secret, spec_key, blob)
    # data-only allowlist regardless of sealing: the spec never needs
    # to execute code, so it never gets to
    spec = restricted_loads(blob)
    server = WorkerServer(spec, args.worker_id, secret=secret)

    def _sigterm(_signum, _frame):
        # graceful kill: spill the flight ring so the supervisor's
        # death dump can attach it, then exit hard (the serve loop
        # holds no state worth unwinding)
        try:
            rec = getattr(server.engine, "recorder", None)
            if rec is not None:
                rec.spill()
        finally:
            os._exit(0)

    signal.signal(signal.SIGTERM, _sigterm)

    from ..distributed._framing import seal
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((args.bind_host, 0))
    srv.listen(8)
    port = srv.getsockname()[1]

    def publish(key: str, value: bytes) -> None:
        store.set(key, seal(secret, key, value)
                  if secret is not None else value)

    publish(f"{args.prefix}/{args.worker_id}/pid",
            str(os.getpid()).encode())
    publish(f"{args.prefix}/{args.worker_id}/host",
            advertise.encode("utf-8"))
    # port LAST: the supervisor waits on it, so host/pid are already
    # readable when the wait returns
    publish(f"{args.prefix}/{args.worker_id}/port",
            str(port).encode())
    store.close()
    server.serve(srv)


if __name__ == "__main__":
    main()
