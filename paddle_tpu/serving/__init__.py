"""paddle_tpu.serving — continuous-batching LLM serving engine.

Iteration-level scheduling (Orca) over a BLOCK-PAGED KV cache
(PagedAttention-style fixed-size pages + static per-slot page tables,
copy-on-write prefix sharing keyed by prompt content, optional int8
KV with per-page scales — all inside the repo's compile-once decode
design): one compiled decode-step program serves ANY mix of in-flight
requests, admission is gated by free PAGES (worst-case span reserved,
so decode never preempts), and finished sequences (EOS / length cap)
are evicted immediately, their shared prompt pages staying cached for
later requests. ``kv_layout="contiguous"`` selects the original
slot-pool flavor (fixed ``(max_slots, max_len)`` rows) for A/B.

    engine = ServingEngine(model, max_slots=8, max_len=512, eos_id=2)
    req = engine.submit(prompt_ids, max_new_tokens=64)
    done = engine.run()            # or step() per iteration
    print(req.output_ids, engine.metrics.summary())

Compile count is 1 decode program + O(log max_len) prefill/extend
buckets (+1 COW copy program), asserted in
tests/test_serving_engine.py + tests/test_paged_kv.py via trace
counting — paging adds ZERO decode compiles.

``speculative=True`` turns on SELF-SPECULATIVE decoding: an n-gram /
prompt-lookup proposer (``spec_decode.NgramProposer``, no second
model) drafts up to ``spec_k - 1`` tokens per greedy row per step and
ONE widened verify program scores all k candidate positions in a
single weight pass, emitting the longest accepted prefix — provably
token-identical to non-speculative greedy decode (the acceptance rule
IS sequential greedy run k steps ahead; tests/test_spec_decode.py).
Rows with no usable draft run at k=1 inside the same program.

``mesh=`` (a ProcessMesh with a ``model`` axis) makes the engine
TENSOR-PARALLEL — KV pools and shardable params split across chips,
one decode program per mesh shape, greedy outputs bitwise identical
to single-chip — and ``prefill_devices=k`` DISAGGREGATES prefill from
decode with an explicit KV handoff between the two chip groups
(serving/mesh.py, docs/SERVING.md "Multi-chip serving").

Failure contract (docs/RESILIENCE.md): typed errors in ``errors``
(``QueueFull`` / ``DeadlineExceeded`` / ``EngineBroken`` /
``EngineIdle`` / ``EngineClosed``), ``ServingEngine.recover()`` after
a donated-pool step failure, per-request ``deadline_s``, bounded
``max_queue`` admission, and ``drain()`` for graceful shutdown.
"""
from .cluster import (ClusterSupervisor, RemoteEngine,  # noqa: F401
                      RemoteReplica, WorkerHandle)
from .engine import ServingEngine  # noqa: F401
from .control import (Actuator, BrownoutController,  # noqa: F401
                      ChunkBudgetController, ControlPlane,
                      PrefixAffinityPolicy, ReplicaAutoscaler)
from .errors import (DeadlineExceeded, EngineBroken,  # noqa: F401
                     EngineClosed, EngineIdle, NoHealthyReplicas,
                     QueueFull, RateLimited, RemoteError, ReplicaDead,
                     RequestCancelled, ServingError, Shed,
                     TenantQueueFull)
from .frontdoor import (ClientStream, FrontDoor,  # noqa: F401
                        FrontDoorHandle, FrontDoorHTTPServer,
                        TenantPolicy, TokenBucket)
from .mesh import MeshContext  # noqa: F401
from .metrics import EngineMetrics  # noqa: F401
from .router import Replica, ReplicaRouter  # noqa: F401
from .sampling import SamplingParams, sample_token  # noqa: F401
from .scheduler import (FIFOScheduler, Request, bucket_for,  # noqa: F401
                        prefill_buckets)
from .slot_cache import PagedKVCache, SlotKVCache  # noqa: F401
from .spec_decode import (DraftModelProposer,  # noqa: F401
                          NgramProposer)
from .spec_tune import SpecTuner  # noqa: F401

__all__ = ["ServingEngine", "EngineMetrics", "MeshContext",
           "SamplingParams",
           "sample_token", "FIFOScheduler", "Request", "bucket_for",
           "prefill_buckets", "SlotKVCache", "PagedKVCache",
           "NgramProposer", "DraftModelProposer", "SpecTuner",
           "ServingError",
           "QueueFull", "DeadlineExceeded", "EngineBroken",
           "EngineIdle", "EngineClosed", "RequestCancelled",
           "RateLimited", "TenantQueueFull", "ReplicaDead",
           "NoHealthyReplicas", "RemoteError", "Shed",
           "ReplicaRouter", "Replica",
           "Actuator", "BrownoutController", "ChunkBudgetController",
           "ControlPlane", "PrefixAffinityPolicy",
           "ReplicaAutoscaler",
           "ClusterSupervisor", "RemoteEngine", "RemoteReplica",
           "WorkerHandle",
           "FrontDoor", "FrontDoorHTTPServer", "FrontDoorHandle",
           "ClientStream", "TenantPolicy", "TokenBucket"]
