"""Typed serving-engine errors (the engine's failure contract).

Callers branch on these instead of parsing RuntimeError strings:

- :class:`QueueFull` — ``submit()`` with the bounded admission queue at
  ``max_queue``; shed load or apply backpressure upstream.
- :class:`DeadlineExceeded` — a request missed its deadline: set as
  ``Request.error`` (with ``finish_reason == "deadline"``) when the
  engine cancels a queued or in-flight request at a step boundary.
  Never raised by ``submit()`` — whether a deadline is meetable
  depends on the queue ahead of it (a non-positive ``deadline_s`` is a
  ``ValueError``).
- :class:`EngineBroken` — ``step()``/``submit()`` after a step failed
  with donated cache pools; call ``recover()`` to rebuild and resume.
- :class:`EngineIdle` — ``step()`` with no queued or in-flight work
  (guard loops with ``has_work()``).
- :class:`EngineClosed` — ``submit()`` after ``drain()``.
- :class:`RequestCancelled` — set as ``Request.error`` by
  ``cancel()``/``drain(max_steps=...)`` cutoffs.
"""
from __future__ import annotations

__all__ = ["ServingError", "QueueFull", "DeadlineExceeded",
           "EngineBroken", "EngineIdle", "EngineClosed",
           "RequestCancelled"]


class ServingError(RuntimeError):
    """Base class for the serving engine's typed failures."""


class QueueFull(ServingError):
    def __init__(self, depth: int, max_queue: int):
        super().__init__(
            f"admission queue full ({depth} waiting >= max_queue="
            f"{max_queue}); retry later or raise max_queue")
        self.depth = depth
        self.max_queue = max_queue


class DeadlineExceeded(ServingError):
    def __init__(self, rid, detail: str = ""):
        super().__init__(
            f"request {rid} missed its deadline"
            + (f": {detail}" if detail else ""))
        self.rid = rid


class EngineBroken(ServingError):
    def __init__(self, reason: str):
        super().__init__(
            f"ServingEngine is broken (a step failed after its cache "
            f"pools were donated — device buffers invalidated): "
            f"{reason}. Call recover() to rebuild the KV pools from "
            f"host-side request state and resume; the flight-recorder "
            f"dump has the post-mortem.")
        self.reason = reason


class EngineIdle(ServingError):
    def __init__(self):
        super().__init__(
            "step() called with no queued or in-flight work; guard the "
            "loop with has_work()")


class EngineClosed(ServingError):
    def __init__(self):
        super().__init__(
            "ServingEngine is draining/closed; submit() refused")


class RequestCancelled(ServingError):
    def __init__(self, rid, reason: str = "cancelled"):
        super().__init__(f"request {rid} cancelled: {reason}")
        self.rid = rid
