"""Typed serving-engine errors (the engine's failure contract).

Callers branch on these instead of parsing RuntimeError strings:

- :class:`QueueFull` — ``submit()`` with the bounded admission queue at
  ``max_queue``; shed load or apply backpressure upstream.
- :class:`DeadlineExceeded` — a request missed its deadline: set as
  ``Request.error`` (with ``finish_reason == "deadline"``) when the
  engine cancels a queued or in-flight request at a step boundary.
  Never raised by ``submit()`` — whether a deadline is meetable
  depends on the queue ahead of it (a non-positive ``deadline_s`` is a
  ``ValueError``).
- :class:`EngineBroken` — ``step()``/``submit()`` after a step failed
  with donated cache pools; call ``recover()`` to rebuild and resume.
- :class:`EngineIdle` — ``step()`` with no queued or in-flight work
  (guard loops with ``has_work()``).
- :class:`EngineClosed` — ``submit()`` after ``drain()``.
- :class:`RequestCancelled` — set as ``Request.error`` by
  ``cancel()``/``drain(max_steps=...)`` cutoffs, and (with reason
  ``"disconnect"``) when the front door observes the client gone.

Front-door / router additions (serving/frontdoor.py, serving/router.py):

- :class:`RateLimited` — a tenant exceeded its token-bucket rate; the
  carried ``retry_after_s`` is the earliest the bucket refills.
- :class:`TenantQueueFull` — a tenant hit its per-tenant in-flight cap
  (tenant isolation: one tenant's backlog cannot starve the others).
- :class:`Shed` — the brownout controller rejected a low-priority
  request under overload (serving/control.py); an *audited* rejection
  at the client boundary (HTTP 503 + Retry-After), never a LOST
  request.
- :class:`ReplicaDead` — a replica is gone (health probe, or raised
  out of a dying replica's step); the router fails its in-flight
  requests over to peers.
- :class:`NoHealthyReplicas` — the router has no live replica to
  dispatch to; shed load upstream.
"""
from __future__ import annotations

__all__ = ["ServingError", "QueueFull", "DeadlineExceeded",
           "EngineBroken", "EngineIdle", "EngineClosed",
           "RequestCancelled", "RateLimited", "TenantQueueFull",
           "Shed", "ReplicaDead", "NoHealthyReplicas", "RemoteError"]


def _rebuild_error(cls, args, attrs):
    # bypass the subclass __init__ (whose signature is structured, not
    # (message,)): restore message via RuntimeError and attributes
    # (rid, tenant, retry_after_s, ...) from __dict__
    e = cls.__new__(cls)
    RuntimeError.__init__(e, *args)
    e.__dict__.update(attrs)
    return e


class ServingError(RuntimeError):
    """Base class for the serving engine's typed failures.

    Pickle-safe by construction: these cross the serving-cluster RPC
    boundary (serving/cluster.py ships a worker's typed refusal back
    to the router), and default exception pickling would call the
    subclass ``__init__`` with the formatted message — a TypeError for
    every subclass with a structured signature.
    """

    def __reduce__(self):
        return _rebuild_error, (type(self), self.args, dict(self.__dict__))


class QueueFull(ServingError):
    def __init__(self, depth: int, max_queue: int):
        super().__init__(
            f"admission queue full ({depth} waiting >= max_queue="
            f"{max_queue}); retry later or raise max_queue")
        self.depth = depth
        self.max_queue = max_queue


class DeadlineExceeded(ServingError):
    def __init__(self, rid, detail: str = ""):
        super().__init__(
            f"request {rid} missed its deadline"
            + (f": {detail}" if detail else ""))
        self.rid = rid


class EngineBroken(ServingError):
    def __init__(self, reason: str):
        super().__init__(
            f"ServingEngine is broken (a step failed after its cache "
            f"pools were donated — device buffers invalidated): "
            f"{reason}. Call recover() to rebuild the KV pools from "
            f"host-side request state and resume; the flight-recorder "
            f"dump has the post-mortem.")
        self.reason = reason


class EngineIdle(ServingError):
    def __init__(self):
        super().__init__(
            "step() called with no queued or in-flight work; guard the "
            "loop with has_work()")


class EngineClosed(ServingError):
    def __init__(self):
        super().__init__(
            "ServingEngine is draining/closed; submit() refused")


class RequestCancelled(ServingError):
    def __init__(self, rid, reason: str = "cancelled"):
        super().__init__(f"request {rid} cancelled: {reason}")
        self.rid = rid


class RateLimited(ServingError):
    def __init__(self, tenant: str, retry_after_s: float = 0.0):
        super().__init__(
            f"tenant {tenant!r} rate-limited; retry in "
            f"{retry_after_s:.3f}s")
        self.tenant = tenant
        self.retry_after_s = retry_after_s


class TenantQueueFull(ServingError):
    def __init__(self, tenant: str, depth: int, max_inflight: int):
        super().__init__(
            f"tenant {tenant!r} has {depth} requests in flight "
            f">= max_inflight={max_inflight}")
        self.tenant = tenant
        self.depth = depth
        self.max_inflight = max_inflight


class Shed(ServingError):
    def __init__(self, tenant: str, tier: int,
                 retry_after_s: float = 0.0):
        super().__init__(
            f"tenant {tenant!r} shed at brownout (tier {tier}); "
            f"retry in {retry_after_s:.3f}s")
        self.tenant = tenant
        self.tier = tier
        self.retry_after_s = retry_after_s


class ReplicaDead(ServingError):
    def __init__(self, detail: str = ""):
        super().__init__(
            "replica is dead" + (f": {detail}" if detail else ""))
        self.detail = detail


class NoHealthyReplicas(ServingError):
    def __init__(self, total: int):
        super().__init__(
            f"no healthy replica to dispatch to ({total} registered, "
            f"all draining or dead)")
        self.total = total


class RemoteError(ServingError):
    """A cluster worker raised an exception that cannot itself cross
    the pickle boundary (unknown type, unpicklable payload); carries
    the type name and rendered message instead."""

    def __init__(self, type_name: str, detail: str):
        super().__init__(f"worker raised {type_name}: {detail}")
        self.type_name = type_name
        self.detail = detail
