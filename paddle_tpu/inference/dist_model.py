"""Multi-rank served models: pipeline inference over FleetExecutor
actors.

Reference: paddle/fluid/distributed/fleet_executor/dist_model.cc —
DistModel::Init loads one program partition per rank and Run() drives
feed → fleet-executor pipeline → fetch over brpc. TPU-native version:
each stage is an exported StableHLO artifact served by a Predictor
(its own AOT-compiled XLA program); stages are chained by the actor
Carrier/Interceptor runtime (distributed/fleet_executor.py) with
credit-based micro-batch flow, so stage k runs micro-batch i while
stage k+1 runs micro-batch i-1 — host-side pipeline parallelism for
serving, the inference analog of the training schedules.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np

from . import Config, Predictor, create_predictor
from ..distributed.fleet_executor import FleetExecutor

__all__ = ["DistModelConfig", "DistModel"]


class DistModelConfig:
    """dist_model.h DistModelConfig analog: the per-stage model paths
    plus pipeline knobs."""

    def __init__(self, model_prefixes: Sequence[str],
                 precision=None, num_micro_batches: int = 2,
                 buffer_size: int = 2):
        if not model_prefixes:
            raise ValueError("need at least one stage model")
        self.model_prefixes = list(model_prefixes)
        self.precision = precision
        self.num_micro_batches = int(num_micro_batches)
        self.buffer_size = int(buffer_size)


class DistModel:
    """Serve a model split into pipeline stages, each an exported
    artifact; `run(feed)` pipelines micro-batches through the stages."""

    def __init__(self, config: DistModelConfig):
        self._config = config
        self._predictors: List[Predictor] = []
        self._initialized = False

    def init(self) -> bool:
        if self._initialized:
            return True
        for prefix in self._config.model_prefixes:
            c = Config(prefix)
            if self._config.precision is not None:
                c.set_precision(self._config.precision)
            self._predictors.append(create_predictor(c))
        # the actor graph depends only on the stage fns: build once;
        # run() spins a fresh carrier over it per batch
        self._executor = FleetExecutor(
            [self._stage_fn(i) for i in range(len(self._predictors))],
            num_micro_batches=self._config.num_micro_batches,
            buffer_size=self._config.buffer_size)
        self._initialized = True
        return True

    def _stage_fn(self, idx: int):
        pred = self._predictors[idx]

        def run(payload):
            outs = pred.run(list(payload) if isinstance(
                payload, (list, tuple)) else [payload])
            outs = [o.copy_to_cpu() for o in outs]
            return outs if len(outs) > 1 else outs[0]

        return run

    def run(self, feed: Sequence[Any],
            timeout: float = 300.0) -> List[np.ndarray]:
        """Run one batch: ``feed`` is split into ``num_micro_batches``
        along axis 0, pipelined through the stages, and re-concatenated
        (dist_model.cc Run feed→fetch)."""
        if not self._initialized:
            self.init()
        M = self._config.num_micro_batches
        feed = [np.asarray(getattr(x, "_data", x)) for x in feed]
        B = feed[0].shape[0]
        if B % M:
            raise ValueError(f"batch {B} not divisible by "
                             f"{M} micro-batches")
        micro = [[x[i * (B // M):(i + 1) * (B // M)] for x in feed]
                 for i in range(M)]
        outs = self._executor.run(micro, timeout=timeout)
        first = outs[0]
        if isinstance(first, (list, tuple)):
            return [np.concatenate([np.asarray(o[j]) for o in outs])
                    for j in range(len(first))]
        return [np.concatenate([np.asarray(o) for o in outs])]
