"""paddle_tpu.inference — deployment/serving API.

Reference: paddle/fluid/inference (AnalysisPredictor,
`paddle_inference_api.h` CreatePredictor/Config; python surface
python/paddle/inference/__init__.py). The reference's inference stack is an
IR-pass pipeline (~290 fusion passes) + TensorRT subgraph engine over a saved
ProgramDesc. TPU-native: the saved artifact is serialized StableHLO
(produced by ``paddle_tpu.jit.save``); "analysis passes" are XLA's job, so
the Predictor is a thin, fast runner: deserialize → jit (AOT compile) →
zero-copy handles → run.

API parity surface:
    config = Config(model_prefix)            # AnalysisConfig analog
    config.enable_memory_optim()
    predictor = create_predictor(config)
    names = predictor.get_input_names()
    h = predictor.get_input_handle(names[0]); h.copy_from_cpu(x)
    predictor.run()
    out = predictor.get_output_handle(predictor.get_output_names()[0])
    y = out.copy_to_cpu()
"""
from __future__ import annotations

import enum
import json
import os
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "Config", "Predictor", "create_predictor", "PrecisionType",
    "PlaceType", "Tensor", "get_version",
    "ServingEngine", "SamplingParams",
]


def get_version() -> str:
    from .. import __version__
    return __version__


class PrecisionType(enum.Enum):
    """Reference: paddle_infer::PrecisionType (paddle_inference_api.h)."""
    Float32 = 0
    Half = 1     # on TPU, mapped to bfloat16 (no fp16 MXU path)
    Bfloat16 = 2
    Int8 = 3


class PlaceType(enum.Enum):
    UNK = -1
    CPU = 0
    GPU = 1
    TPU = 2


class Tensor:
    """Zero-copy I/O handle (reference: paddle_infer::Tensor / ZeroCopyTensor,
    paddle/fluid/inference/api/details/zero_copy_tensor.cc)."""

    def __init__(self, name: str, spec: jax.ShapeDtypeStruct):
        self.name = name
        self._spec = spec
        self._value: Optional[jax.Array] = None

    @property
    def shape(self) -> List[int]:
        if self._value is not None:
            return list(self._value.shape)
        return list(self._spec.shape)

    def reshape(self, shape: Sequence[int]):
        # kept for API compat; non-int dims in the spec are jax.export
        # symbolic dims (dynamic-batch exports) and accept any size
        spec_shape = tuple(self._spec.shape)
        if len(shape) != len(spec_shape) or any(
                isinstance(s, int) and s != g
                for s, g in zip(spec_shape, shape)):
            raise ValueError(
                f"input '{self.name}' was exported with shape "
                f"{spec_shape}; got {tuple(shape)}. Re-export "
                "with jit.save(input_spec=...) for the new shape.")

    def type(self):
        return self._spec.dtype

    def copy_from_cpu(self, data) -> None:
        arr = np.asarray(data)
        spec_shape = tuple(self._spec.shape)
        if len(arr.shape) != len(spec_shape) or any(
                isinstance(s, int) and s != a
                for s, a in zip(spec_shape, arr.shape)):
            # non-int dims are jax.export symbolic dims: any size is valid
            raise ValueError(
                f"input '{self.name}' expects shape {spec_shape}"
                f", got {arr.shape}")
        self._value = jnp.asarray(arr, dtype=self._spec.dtype)

    # share_external_data = zero-copy adopt of an existing device array
    def share_external_data(self, tensor) -> None:
        data = getattr(tensor, "_data", tensor)
        self._value = jnp.asarray(data, dtype=self._spec.dtype)

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError(f"tensor '{self.name}' has no data; run() "
                               "the predictor first")
        return np.asarray(self._value)

    def lod(self):
        return []

    def set_lod(self, lod):
        pass


class Config:
    """AnalysisConfig analog (reference:
    paddle/fluid/inference/api/analysis_config.cc). Holds the model path and
    execution knobs; graph optimization toggles are accepted for parity but
    XLA owns fusion/memory planning on TPU."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # reference passes (model, params); jit.save emits one prefix
        self._prefix = None
        if prog_file is not None:
            self._prefix = self._strip(prog_file)
        self._precision = PrecisionType.Float32
        self._device = PlaceType.TPU
        self._memory_optim = True
        self._ir_optim = True
        self._donate_inputs = False
        self._math_threads = 1

    @staticmethod
    def _strip(path: str) -> str:
        for suf in (".stablehlo.mlir", ".pdiparams", ".pdmeta", ".pdmodel"):
            if path.endswith(suf):
                return path[: -len(suf)]
        return path

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        self._prefix = self._strip(prog_file)

    def model_dir(self) -> Optional[str]:
        return os.path.dirname(self._prefix) if self._prefix else None

    def prog_file(self) -> str:
        return self._prefix + ".stablehlo.mlir"

    def params_file(self) -> str:
        return self._prefix + ".pdiparams"

    # --- device / precision -------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100,
                       device_id: int = 0, precision=PrecisionType.Float32):
        # accepted for parity; execution targets the default JAX backend
        self._device = PlaceType.TPU
        self._precision = precision

    def enable_tpu(self, precision=PrecisionType.Float32):
        self._device = PlaceType.TPU
        self._precision = precision

    def disable_gpu(self):
        self._device = PlaceType.CPU

    def use_gpu(self) -> bool:
        return self._device in (PlaceType.GPU, PlaceType.TPU)

    def set_precision(self, precision: PrecisionType):
        self._precision = precision

    def precision(self) -> PrecisionType:
        return self._precision

    # --- optimization toggles (parity; XLA does the work) -------------------
    def enable_memory_optim(self, x: bool = True):
        self._memory_optim = x

    def switch_ir_optim(self, x: bool = True):
        self._ir_optim = x

    def switch_ir_debug(self, x: bool = True):
        pass

    def enable_mkldnn(self):
        pass

    def set_cpu_math_library_num_threads(self, n: int):
        self._math_threads = int(n)

    def switch_use_feed_fetch_ops(self, x: bool = False):
        pass

    def switch_specify_input_names(self, x: bool = True):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        raise RuntimeError("TensorRT is a CUDA engine; on TPU the exported "
                           "StableHLO is compiled by XLA directly")

    # buffer donation: lets XLA reuse input buffers for outputs
    def enable_input_donation(self, x: bool = True):
        self._donate_inputs = x

    def summary(self) -> str:
        return json.dumps({
            "model": self._prefix, "precision": self._precision.name,
            "device": self._device.name, "memory_optim": self._memory_optim,
        }, indent=2)


class Predictor:
    """AnalysisPredictor analog (reference:
    paddle/fluid/inference/api/analysis_predictor.h:105; ZeroCopyRun :215).

    Deserializes the StableHLO program, AOT-compiles it once (the analog of
    OptimizeInferenceProgram — XLA runs fusion/layout/memory passes), and
    executes with zero host↔device copies between run() calls."""

    def __init__(self, config: Config):
        if config._prefix is None:
            raise ValueError("Config has no model path; use Config(prefix)")
        self._config = config
        prefix = config._prefix

        from ..jit.save_load import load_artifacts
        self._exported, params, buffers = load_artifacts(prefix)

        self._int8_scales = None
        if config._precision in (PrecisionType.Half, PrecisionType.Bfloat16):
            # Weight-only bf16: halve HBM for weights; the convert back to
            # the program's traced dtype is fused into the consuming dot by
            # XLA. (The program's compute dtypes are fixed at export time —
            # export under amp/bf16 for full low-precision compute.)
            cast = lambda t: (t.astype(jnp.bfloat16)
                              if jnp.issubdtype(t.dtype, jnp.floating) else t)
            params = {k: cast(v) for k, v in params.items()}
            buffers = {k: cast(v) for k, v in buffers.items()}
            self._weight_only = True
        elif config._precision == PrecisionType.Int8:
            # Weight-only int8 (reference: TRT int8 / weight-only-quant
            # passes): params stored as int8 + per-channel scales (4x
            # less weight HBM traffic); dequant runs INSIDE the jitted
            # program so XLA fuses it into consumers. For REAL int8
            # compute (activations too), export a PTQ
            # convert(real=True) model — its program already carries
            # int8 dots and needs no Config flag.
            from ..quantization.int8_layers import _quantize_weight
            self._int8_scales = {}
            qparams = {}
            for k, v in params.items():
                # matrices and conv filters only: 1-D vectors would
                # carry a same-sized fp32 scale (negative compression)
                if jnp.issubdtype(v.dtype, jnp.floating) \
                        and v.ndim >= 2 and v.size > 256:
                    axis = 0 if v.ndim >= 3 else (v.ndim - 1)
                    q, scale = _quantize_weight(v, axis)
                    qparams[k] = jnp.asarray(q)
                    self._int8_scales[k] = (jnp.asarray(scale), v.dtype)
                else:
                    qparams[k] = v
            params = qparams
            self._weight_only = True
        else:
            self._weight_only = False
        self._params = params
        self._buffers = buffers

        with open(prefix + ".pdmeta") as f:
            meta = json.load(f)
        self._input_names: List[str] = []
        self._inputs: Dict[str, Tensor] = {}
        # in_avals is the flattened pytree [*param_leaves, *buffer_leaves,
        # *inputs]; the declared inputs are the trailing entries.
        n_in = len(meta["input_specs"])
        in_avals = self._exported.in_avals[-n_in:] if n_in else []
        for i, (spec, aval) in enumerate(zip(meta["input_specs"], in_avals)):
            name = spec.get("name") or f"x{i}"
            self._input_names.append(name)
            self._inputs[name] = Tensor(name, jax.ShapeDtypeStruct(
                tuple(aval.shape), aval.dtype))

        self._outputs: Dict[str, Tensor] = {}
        self._output_names: List[str] = []
        self._compiled = None

    # --- introspection ------------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        if not self._output_names:
            n = len(self._exported.out_avals)
            self._output_names = [f"output_{i}" for i in range(n)]
        return list(self._output_names)

    def get_output_handle(self, name: str) -> Tensor:
        if name not in self._outputs:
            idx = int(name.rsplit("_", 1)[1])
            aval = self._exported.out_avals[idx]
            self._outputs[name] = Tensor(name, aval)
        return self._outputs[name]

    # --- execution ----------------------------------------------------------
    def _fn(self, params, buffers, *args):
        if self._int8_scales:
            params = {k: (v.astype(jnp.float32)
                          * self._int8_scales[k][0]).astype(
                              self._int8_scales[k][1])
                      if k in self._int8_scales else v
                      for k, v in params.items()}
        flat, treedef = jax.tree.flatten((params, buffers, *args))
        flat = [x.astype(av.dtype) if x.dtype != av.dtype else x
                for x, av in zip(flat, self._exported.in_avals)]
        params, buffers, *args = jax.tree.unflatten(treedef, flat)
        return self._exported.call(params, buffers, *args)

    def run(self, inputs: Optional[Sequence] = None):
        """ZeroCopyRun. With ``inputs`` given, behaves like the reference's
        convenience ``predictor.run([t0, t1])`` and returns outputs."""
        if inputs is not None:
            for name, x in zip(self._input_names, inputs):
                data = getattr(x, "_data", x)
                self._inputs[name]._value = jnp.asarray(data)
        args = []
        for name in self._input_names:
            h = self._inputs[name]
            if h._value is None:
                raise RuntimeError(f"input '{name}' not set; call "
                                   "copy_from_cpu first")
            args.append(h._value)
        if self._compiled is None:
            donate = (tuple(range(2, 2 + len(args)))
                      if self._config._donate_inputs else ())
            self._compiled = jax.jit(self._fn, donate_argnums=donate)
        outs = self._compiled(self._params, self._buffers, *args)
        # exported programs may return nested pytrees (tuples/dicts); the
        # handle set is the flattened leaves, matching out_avals order
        outs = jax.tree.leaves(outs)
        for i, o in enumerate(outs):
            self.get_output_handle(f"output_{i}")._value = o
        self._output_names = [f"output_{i}" for i in range(len(outs))]
        if inputs is not None:
            return [self._outputs[n] for n in self._output_names]
        return True

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass

    def clone(self) -> "Predictor":
        """Share weights + compiled executable with a new handle set
        (reference AnalysisPredictor::Clone shares the scope)."""
        p = Predictor.__new__(Predictor)
        p.__dict__.update(self.__dict__)
        p._inputs = {n: Tensor(n, t._spec) for n, t in self._inputs.items()}
        p._outputs = {}
        p._output_names = []
        return p


def create_predictor(config: Config) -> Predictor:
    """paddle_infer::CreatePredictor analog."""
    return Predictor(config)


# Continuous-batching LLM serving (paged-KV scheduler with COW prefix
# sharing over the compile-once decode path) — full docs in
# paddle_tpu/serving.
from ..serving import SamplingParams, ServingEngine  # noqa: E402,F401


def convert_to_mixed_precision(model_file: str, params_file: str,
                               mixed_model_file: str,
                               mixed_params_file: str,
                               mixed_precision=PrecisionType.Bfloat16,
                               backend=PlaceType.TPU,
                               keep_io_types: bool = True,
                               black_list=None):
    """Offline weight conversion (reference:
    paddle/fluid/inference/analysis/passes/convert_to_mixed_precision.cc).
    On TPU only the weights need converting; compute precision follows the
    weights under XLA."""
    from ..framework.io import load as fw_load, save as fw_save
    from ..framework.tensor import Tensor as FTensor
    prefix = Config._strip(model_file)
    out_prefix = Config._strip(mixed_model_file)
    src_params = (params_file if params_file.endswith(".pdiparams")
                  else Config._strip(params_file) + ".pdiparams")
    dst_params = (mixed_params_file
                  if mixed_params_file.endswith(".pdiparams")
                  else Config._strip(mixed_params_file) + ".pdiparams")
    state = fw_load(src_params)

    def cast(v):
        t = v._data
        if jnp.issubdtype(t.dtype, jnp.floating):
            return FTensor(t.astype(jnp.bfloat16))
        return v
    state = {grp: {k: cast(v) for k, v in d.items()}
             for grp, d in state.items()}
    import shutil
    if out_prefix != prefix:
        shutil.copyfile(prefix + ".stablehlo.mlir",
                        out_prefix + ".stablehlo.mlir")
        shutil.copyfile(prefix + ".pdmeta", out_prefix + ".pdmeta")
    fw_save(state, dst_params)
