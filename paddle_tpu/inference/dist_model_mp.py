"""Cross-process DistModel: one OS process per pipeline stage.

Reference: paddle/fluid/distributed/fleet_executor/dist_model.cc — each
RANK is a process that loads its program partition; Run() feeds rank 0,
activations flow rank->rank over brpc, fetch comes from the last rank.

TPU-native version: every stage is an exported StableHLO artifact
served by a Predictor inside its own ``python -m
paddle_tpu.inference.dist_model_mp`` worker process (own XLA runtime,
own device context — the process isolation the in-process
``DistModel`` actors do not give). Activations travel stage->stage
over persistent length-prefixed sockets (the rpc/tcp_store transport
family, csrc/tcp_store.cc style framing), so stage k runs micro-batch
i while stage k+1 runs micro-batch i-1. The driver keeps a credit
window of in-flight micro-batches for backpressure, like the
interceptor buffer_size in the in-process engine.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Any, List, Sequence

import numpy as np

from .dist_model import DistModelConfig

__all__ = ["DistModelMP", "DistModelConfig"]


from ..distributed._framing import (nodelay as _nodelay,
                                    send_msg, recv_msg)


def _send(sock: socket.socket, obj) -> None:
    send_msg(sock, pickle.dumps(obj,
                                protocol=pickle.HIGHEST_PROTOCOL))


def _recv(sock: socket.socket):
    try:
        data = recv_msg(sock, eof_ok=True)
    except ConnectionError:
        return None
    return None if data is None else pickle.loads(data)


def _worker_main(model_prefix: str, listen_port: int, next_addr: str,
                 precision: str) -> None:
    """One pipeline stage: serve Predictor.run over the socket chain."""
    from . import Config, create_predictor, PrecisionType

    cfg = Config(model_prefix)
    if precision == "int8":
        cfg.set_precision(PrecisionType.Int8)
    elif precision == "half":
        cfg.set_precision(PrecisionType.Half)
    pred = create_predictor(cfg)

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", listen_port))
    srv.listen(1)
    # readiness handshake: the driver connects only after the stage
    # printed its port (predictor load can take seconds)
    sys.stdout.write(f"READY {srv.getsockname()[1]}\n")
    sys.stdout.flush()

    nxt = None
    if next_addr:
        host, port = next_addr.rsplit(":", 1)
        deadline = time.time() + 60
        while True:
            try:
                nxt = _nodelay(socket.create_connection((host, int(port))))
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.05)

    conn, _ = srv.accept()
    _nodelay(conn)
    try:
        # diagnostic dwell per micro-batch: lets a 1-core host DEMONSTRATE
        # the pipeline's stage overlap (sleeps overlap where CPU-bound
        # compute cannot; tests/test_dist_model_mp.py asserts the
        # (M + S - 1) x dwell pipelined wall against the M x S serial one).
        # Honored ONLY under an explicit debug marker or on the cpu
        # platform — an operator inheriting the env var from a test
        # session must not silently slow every production request.
        dwell_s = float(os.environ.get("PTPU_STAGE_DWELL_MS", "0")) / 1e3
        if dwell_s:
            import jax
            if not (os.environ.get("PTPU_STAGE_DWELL_DEBUG")
                    or jax.default_backend() == "cpu"):
                sys.stderr.write(
                    "PTPU_STAGE_DWELL_MS set but ignored: stage runs on "
                    f"'{jax.default_backend()}' and "
                    "PTPU_STAGE_DWELL_DEBUG is unset\n")
                dwell_s = 0.0
            else:
                sys.stderr.write(  # log once, loudly — never silent
                    f"stage dwell ACTIVE: {dwell_s * 1e3:.0f} ms per "
                    "micro-batch (PTPU_STAGE_DWELL_MS diagnostic)\n")
            sys.stderr.flush()
        while True:
            msg = _recv(conn)
            if msg is None or msg[0] == "stop":
                break
            tag, payload = msg
            outs = pred.run([np.asarray(x) for x in payload])
            outs = [o.copy_to_cpu() for o in outs]
            if dwell_s:
                time.sleep(dwell_s)
            _send(nxt if nxt is not None else conn, (tag, outs))
        if nxt is not None:
            _send(nxt, ("stop", None))
    finally:
        conn.close()
        if nxt is not None:
            nxt.close()
        srv.close()


class DistModelMP:
    """Serve pipeline stages across PROCESSES (dist_model.cc Run).

    The driver connects to stage 0 and receives fetches from the LAST
    stage; intermediate activations never pass through the driver."""

    def __init__(self, config: DistModelConfig):
        self._config = config
        self._procs: List[subprocess.Popen] = []
        self._feed_sock = None
        self._fetch_srv = None
        self._fetch_sock = None
        self._initialized = False

    def init(self) -> bool:
        if self._initialized:
            return True
        n = len(self._config.model_prefixes)
        precision = ""
        p = self._config.precision
        if p is not None:
            precision = getattr(p, "name", str(p)).lower()
            precision = {"int8": "int8", "half": "half"}.get(
                precision, "")
        # the LAST stage sends fetches back to the driver
        self._fetch_srv = socket.socket(socket.AF_INET,
                                        socket.SOCK_STREAM)
        self._fetch_srv.setsockopt(socket.SOL_SOCKET,
                                   socket.SO_REUSEADDR, 1)
        self._fetch_srv.bind(("127.0.0.1", 0))
        self._fetch_srv.listen(1)
        fetch_port = self._fetch_srv.getsockname()[1]

        # PYTHONPATH handling is platform-dependent: the axon TPU
        # plugin registers through PYTHONPATH in current images AND
        # its site dir forces the accelerator backend onto any child
        # that can import it (JAX_PLATFORMS=cpu does not win). So the
        # default CPU workers strip PYTHONPATH wholesale (load-
        # bearing: a kept axon site hijacks them onto the chip and
        # their cpu-exported StableHLO refuses to run), while workers
        # explicitly pointed at an accelerator via
        # PTPU_DIST_MODEL_PLATFORM keep the non-repo entries the
        # plugin needs. Repo imports ride sys.argv[4] below.
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        platform = os.environ.get("PTPU_DIST_MODEL_PLATFORM", "cpu")
        if platform == "cpu":
            env = {k: v for k, v in os.environ.items()
                   if k != "PYTHONPATH"}
        else:
            env = dict(os.environ)
            pp = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                  if p and os.path.abspath(p) != repo]
            if pp:
                env["PYTHONPATH"] = os.pathsep.join(pp)
            else:
                env.pop("PYTHONPATH", None)
        env["JAX_PLATFORMS"] = platform
        ports: List[int] = []
        try:
            # spawn back to front so each stage can name its successor
            for i in reversed(range(n)):
                nxt = f"127.0.0.1:{fetch_port}" if i == n - 1 \
                    else f"127.0.0.1:{ports[-1]}"
                proc = subprocess.Popen(
                    [sys.executable, "-c",
                     "import sys; sys.path.insert(0, sys.argv[4]); "
                     "from paddle_tpu.inference.dist_model_mp import "
                     "_worker_main; _worker_main(sys.argv[1], 0, "
                     "sys.argv[2], sys.argv[3])",
                     self._config.model_prefixes[i], nxt, precision,
                     repo],
                    env=env, stdout=subprocess.PIPE, stderr=sys.stderr,
                    text=True, cwd=repo)
                self._procs.append(proc)
                import select
                ready, _, _ = select.select([proc.stdout], [], [],
                                            120.0)
                line = proc.stdout.readline().strip() if ready else ""
                if not line.startswith("READY "):
                    raise RuntimeError(
                        f"stage {i} failed to start "
                        f"({'timeout' if not ready else line!r})")
                ports.append(int(line.split()[1]))
            self._procs.reverse()
            ports.reverse()
            self._fetch_srv.settimeout(120.0)
            self._feed_sock = _nodelay(socket.create_connection(
                ("127.0.0.1", ports[0]), timeout=120.0))
            self._feed_sock.settimeout(None)
            self._fetch_sock, _ = self._fetch_srv.accept()
            _nodelay(self._fetch_sock)
        except Exception:
            self._teardown()   # no orphan workers on partial failure
            raise
        self._initialized = True
        return True

    def _teardown(self):
        for p in self._procs:
            if p.poll() is None:
                p.kill()
            try:
                p.wait(timeout=5)   # reap — no zombies left behind
            except subprocess.TimeoutExpired:
                pass
        for s in (self._feed_sock, self._fetch_sock, self._fetch_srv):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._procs = []
        self._feed_sock = self._fetch_sock = self._fetch_srv = None
        self._initialized = False

    def run(self, feed: Sequence[Any],
            timeout: float = 300.0) -> List[np.ndarray]:
        if not self._initialized:
            self.init()
        M = self._config.num_micro_batches
        feed = [np.asarray(getattr(x, "_data", x)) for x in feed]
        B = feed[0].shape[0]
        if B % M:
            raise ValueError(
                f"batch {B} not divisible by {M} micro-batches")
        micro = [[x[i * (B // M):(i + 1) * (B // M)] for x in feed]
                 for i in range(M)]
        window = len(self._procs) + self._config.buffer_size
        results: dict = {}
        err: list = []

        def collect():
            try:
                # the LAST stage always connects back to the fetch
                # server (even when it is also the first stage)
                while len(results) < M:
                    msg = _recv(self._fetch_sock)
                    if msg is None:
                        raise ConnectionError("pipeline closed early")
                    tag, outs = msg
                    results[tag] = outs
            except Exception as e:  # surfaced by the main thread
                err.append(e)

        t = threading.Thread(target=collect, daemon=True)
        t.start()
        sent = 0
        deadline = time.time() + timeout
        while sent < M:
            while sent - len(results) >= window and not err:
                if time.time() > deadline:
                    raise TimeoutError("DistModelMP.run timed out")
                time.sleep(0.001)
            if err:
                break
            # a wedged stage must not block sendall past the deadline
            self._feed_sock.settimeout(
                max(0.01, deadline - time.time()))
            try:
                _send(self._feed_sock, (sent, micro[sent]))
            except socket.timeout:
                err.append(TimeoutError("DistModelMP.run timed out"))
                break
            finally:
                self._feed_sock.settimeout(None)
            sent += 1
        t.join(timeout=max(0.0, deadline - time.time()))
        if err or len(results) < M or t.is_alive():
            # the collector may still hold the fetch socket: a retry
            # with two readers would interleave frames — rebuild the
            # pipeline instead (init() runs again on the next call)
            self._teardown()
            if err and not isinstance(err[0], TimeoutError):
                raise err[0]
            raise TimeoutError("DistModelMP.run timed out")
        first = results[0]
        ordered = [results[i] for i in range(M)]
        return [np.concatenate([np.asarray(o[j]) for o in ordered])
                for j in range(len(first))]

    def close(self):
        if not self._initialized:
            return
        try:
            _send(self._feed_sock, ("stop", None))
        except OSError:
            pass
        for p in self._procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass   # _teardown kills whatever is left
        self._teardown()

    def __enter__(self):
        self.init()
        return self

    def __exit__(self, *exc):
        self.close()
