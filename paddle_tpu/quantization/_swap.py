"""Shared module-swap traversal for the weight-only quantizers."""
from __future__ import annotations

from typing import Callable, Optional

from ..nn.layer_base import Layer

__all__ = ["swap_layers"]


def swap_layers(model: Layer,
                factory: Callable[[Layer], Optional[Layer]],
                inplace: bool = True) -> Layer:
    """Replace sublayers bottom-up: ``factory(child)`` returns the
    replacement layer or None to recurse into the child instead. One
    traversal shared by weight_only_int8/int4 so the deepcopy/inplace
    contract and recursion rules cannot diverge."""
    if not inplace:
        import copy
        model = copy.deepcopy(model)
    for name, child in list(model._sub_layers.items()):
        repl = factory(child)
        if repl is not None:
            model._sub_layers[name] = repl
        else:
            swap_layers(child, factory, inplace=True)
    return model
