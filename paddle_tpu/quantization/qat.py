"""QAT — quantization-aware training (reference:
/root/reference/python/paddle/quantization/qat.py:27 QAT.quantize: walk the
model, replace mapped layer types with quanted wrappers per QuantConfig)."""
from __future__ import annotations

import copy

from ..nn.layer_base import Layer
from .config import QuantConfig


class Quantization:
    def __init__(self, config: QuantConfig):
        self._config = config

    def convert(self, model: Layer, inplace: bool = False,
                remove_quanter: bool = True) -> Layer:
        """Finalize a quantized model for deployment (qat.py:61 analog).

        remove_quanter=True: strip the quanted wrappers, baking the weight
        qdq into the source layer's parameters (deployment form; the
        reference exits to paddle2onnx here, ours re-enters jit/inference
        export). remove_quanter=False: keep wrappers, frozen in eval mode.
        """
        if not inplace:
            model = copy.deepcopy(model)
        if remove_quanter:
            self._strip(model)
        for layer in model.sublayers(include_self=True):
            for q in ("weight_quanter", "activation_quanter"):
                quanter = getattr(layer, q, None)
                if quanter is not None:
                    quanter.eval()
        model.eval()
        return model

    def _strip(self, layer: Layer):
        from .wrapper import _QuantedOpLayer
        for name, child in list(layer.named_children()):
            if isinstance(child, _QuantedOpLayer):
                src = child._source
                if child.weight_quanter is not None:
                    src.weight.set_value(
                        child.weight_quanter(src.weight).detach())
                layer.add_sublayer(name, src)
            else:
                self._strip(child)


class QAT(Quantization):
    def __init__(self, config: QuantConfig):
        super().__init__(config)

    def _quantize_layer(self, parent: Layer, attr_name: str, child: Layer,
                        full_name: str):
        cfg = self._config._get_config_by_layer(child, full_name)
        if cfg is None or not self._config._is_quantifiable(child):
            return
        target = self._config.qat_layer_mappings[type(child)]
        parent.add_sublayer(attr_name, target(child, cfg))

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)
        self._walk(model, "")
        return model

    def _walk(self, layer: Layer, prefix: str):
        for name, child in list(layer.named_children()):
            full = f"{prefix}.{name}" if prefix else name
            if type(child) in self._config.qat_layer_mappings:
                self._quantize_layer(layer, name, child, full)
            elif type(child) in self._config.customized_leaves:
                continue
            else:
                self._walk(child, full)
