"""Real-int8 deployment layers: PTQ-calibrated Linear/Conv2D that
execute on the int8 MXU (294.8 TOPS measured vs 147 bf16 on v5e —
benchmarks/RESULTS.md), not fake-quant simulation.

Reference behavior: the reference's int8 story terminates in a deployed
engine (analysis_predictor + TRT int8 /
paddle/fluid/inference/tensorrt/); its Python quantization module only
simulates. TPU-native version: ``PTQ.convert(model, real=True)`` swaps
observed layers for these, weights pre-quantized per-output-channel,
activations quantized with the CALIBRATED static scale; the int8
dot/conv runs via ``lax.dot_general``/``conv_general_dilated`` with
``preferred_element_type=int32`` (the MXU int8 path), dequant fused
into the epilogue by XLA. ``to_static``/``jit.save`` then export a
program whose hot ops ARE int8, and the inference Predictor serves it
unchanged.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, apply_op
from ..nn.layer_base import Layer

__all__ = ["Int8Linear", "Int8Conv2D", "realize_int8"]


def _quantize_weight(w, axis):
    """Symmetric per-channel int8: returns (q, scale) with w ~= q*scale;
    ``axis`` = the output-channel axis kept in the scale."""
    w = np.asarray(w)
    red = tuple(i for i in range(w.ndim) if i != axis)
    amax = np.max(np.abs(w), axis=red, keepdims=True)
    scale = np.where(amax == 0.0, 1.0, amax) / 127.0
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return q, scale.astype(np.float32)


class Int8Linear(Layer):
    """W8A8 linear with static activation scale (from the PTQ observer)
    and per-out-channel weight scales."""

    def __init__(self, source, act_absmax):
        super().__init__()
        w = source.weight.numpy()          # [in, out]
        q, s = _quantize_weight(w, axis=1)  # scale [1, out]
        self.register_buffer("wq", Tensor(jnp.asarray(q)))
        self.register_buffer("w_scale", Tensor(jnp.asarray(s[0])))
        self.bias = source.bias
        self.act_scale = float(np.asarray(act_absmax).max() / 127.0) \
            if act_absmax is not None else None

    def forward(self, x):
        def f(x, wq, ws, b):
            if self.act_scale is not None:
                xs = jnp.float32(self.act_scale)
                xq = jnp.clip(jnp.round(x.astype(jnp.float32) / xs),
                              -127, 127).astype(jnp.int8)
            else:  # dynamic fallback (uncalibrated)
                amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                               keepdims=True)
                xs = jnp.where(amax == 0.0, 1.0, amax) / 127.0
                xq = jnp.clip(jnp.round(x.astype(jnp.float32) / xs),
                              -127, 127).astype(jnp.int8)
            y = jax.lax.dot_general(
                xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            y = y.astype(jnp.float32) * xs * ws
            if b is not None:
                y = y + b.astype(jnp.float32)
            return y.astype(x.dtype)

        args = [x, self.wq, self.w_scale]
        args.append(self.bias if self.bias is not None else None)
        if isinstance(x, Tensor):
            return apply_op(f, *args, _op_name="int8_linear")
        return f(x, getattr(self.wq, "_data", self.wq),
                 getattr(self.w_scale, "_data", self.w_scale),
                 getattr(self.bias, "_data", self.bias)
                 if self.bias is not None else None)


class Int8Conv2D(Layer):
    """W8A8 NCHW conv with static activation scale; weight [O, I, H, W]
    quantized per-O."""

    def __init__(self, source, act_absmax):
        super().__init__()
        w = source.weight.numpy()
        q, s = _quantize_weight(w, axis=0)  # scale [O,1,1,1]
        self.register_buffer("wq", Tensor(jnp.asarray(q)))
        self.register_buffer(
            "w_scale", Tensor(jnp.asarray(s.reshape(1, -1, 1, 1))))
        self.bias = source.bias
        self.act_scale = float(np.asarray(act_absmax).max() / 127.0) \
            if act_absmax is not None else None
        self._stride = source._stride
        self._padding = source._padding
        self._dilation = source._dilation
        self._groups = source._groups

    def forward(self, x):
        def f(x, wq, ws, b):
            if self.act_scale is not None:
                xs = jnp.float32(self.act_scale)
            else:  # dynamic per-tensor fallback (uncalibrated)
                amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
                xs = jnp.where(amax == 0.0, 1.0, amax) / 127.0
            xq = jnp.clip(jnp.round(x.astype(jnp.float32) / xs),
                          -127, 127).astype(jnp.int8)
            # normalize exactly like the fp conv path does
            from ..nn.functional.conv import _padding, _tuple
            pad = _padding(self._padding, 2)
            stride = _tuple(self._stride, 2)
            dil = _tuple(self._dilation, 2)
            y = jax.lax.conv_general_dilated(
                xq, wq, window_strides=tuple(stride), padding=pad,
                rhs_dilation=tuple(dil),
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                feature_group_count=self._groups,
                preferred_element_type=jnp.int32)
            y = y.astype(jnp.float32) * xs * ws
            if b is not None:
                y = y + b.astype(jnp.float32).reshape(1, -1, 1, 1)
            return y.astype(x.dtype)

        args = [x, self.wq, self.w_scale]
        args.append(self.bias if self.bias is not None else None)
        if isinstance(x, Tensor):
            return apply_op(f, *args, _op_name="int8_conv2d")
        return f(x, getattr(self.wq, "_data", self.wq),
                 getattr(self.w_scale, "_data", self.w_scale),
                 getattr(self.bias, "_data", self.bias)
                 if self.bias is not None else None)


def realize_int8(source: Layer, act_absmax):
    """Map an observed layer to its real-int8 deployment layer, or None
    when no int8 kernel exists for it (caller keeps the qdq fallback)."""
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import Conv2D
    if isinstance(source, Linear):
        return Int8Linear(source, act_absmax)
    if type(source) is Conv2D and source._data_format == "NCHW":
        return Int8Conv2D(source, act_absmax)
    return None


def weight_only_int8(model: Layer, min_features: int = 256,
                     inplace: bool = True) -> Layer:
    """Swap every nn.Linear / NCHW Conv2D in ``model`` for its int8
    deployment layer with DYNAMIC activation scales (no calibration) —
    the weight-only serving recipe: weights live in HBM as int8 +
    per-channel scales (half the bytes of bf16, 4x fp32), which is the
    whole cost of memory-bound decode. Reference analog: the
    weight_only_quant pass family under
    paddle/fluid/inference (analysis_predictor.h:105 int8 story).

    ``min_features``: skip layers whose weight matrix is smaller than
    min_features x min_features — tiny layers gain nothing and per-row
    scale overhead can exceed the win."""
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import Conv2D
    from ._swap import swap_layers

    def factory(child):
        if isinstance(child, Linear):
            if min(child.weight.shape) >= min_features:
                return Int8Linear(child, None)
        elif type(child) is Conv2D and child._data_format == "NCHW":
            if child.weight.shape[1] >= min_features // 4:
                return Int8Conv2D(child, None)
        return None

    return swap_layers(model, factory, inplace=inplace)
