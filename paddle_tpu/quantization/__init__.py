"""paddle_tpu.quantization — QAT / PTQ (reference:
/root/reference/python/paddle/quantization/__init__.py: QuantConfig,
QAT qat.py:27, PTQ ptq.py:29, observers/abs_max.py, quanters/abs_max.py).

TPU-first: fake-quantization is expressed as
``x + stop_gradient(qdq(x) - x)`` — a straight-through estimator that is
pure-functional and jit/pjit-traceable, instead of the reference's
fake_quantize CUDA kernels (paddle/phi/kernels/gpu/fake_quantize_*.cu).
int8 inference flows through the same qdq graph, which XLA folds onto the
MXU's native int8 path when profitable.
"""
from .config import QuantConfig, SingleLayerConfig  # noqa: F401
from .observers import AbsmaxObserver, AVGObserver  # noqa: F401
from .quanters import (  # noqa: F401
    FakeQuanterWithAbsMaxObserver, FakeQuanterChannelWiseAbsMaxObserver)
from .qat import QAT  # noqa: F401
from .ptq import PTQ  # noqa: F401
from .wrapper import (  # noqa: F401
    ObserveWrapper, QuantedLinear, QuantedConv2D, quant_dequant)
from .int8_layers import (  # noqa: F401
    Int8Linear, Int8Conv2D, weight_only_int8)
from .int4_layers import (  # noqa: F401
    Int4Linear, pack_rows_int4, quantize_int4_rows, weight_only_int4)

__all__ = [
    "QuantConfig", "SingleLayerConfig", "AbsmaxObserver", "AVGObserver",
    "FakeQuanterWithAbsMaxObserver",
    "FakeQuanterChannelWiseAbsMaxObserver", "QAT", "PTQ",
    "ObserveWrapper", "QuantedLinear", "QuantedConv2D", "quant_dequant",
    "Int8Linear", "Int8Conv2D", "weight_only_int8",
    "Int4Linear", "weight_only_int4", "quantize_int4_rows",
    "pack_rows_int4",
]
