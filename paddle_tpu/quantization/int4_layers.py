"""Weight-only int4 serving layers: per-group scales, two weights/byte.

Reference analog: the weight_only_quant int4 pass family under
paddle/fluid/inference (analysis_predictor.h int8/int4 story) and
llm.int4-style serving. Storage is EXPLICIT uint8 nibble packing
(ops/int4_matmul.pack_rows_int4 halves layout) consumed by the fused
Pallas unpack-matmul kernel; per-GROUP symmetric scales along the
contraction dim hold accuracy at 4-bit. NOTE the measured verdict
(benchmarks/RESULTS.md round-5): on v5e the VPU unpack cost exceeds
the halved-HBM saving, so int4 decode is SLOWER than the int8-MXU
path — these layers earn their keep on memory capacity (2x model per
chip), not latency.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, apply_op
from ..nn.layer_base import Layer

from ..ops.int4_matmul import (  # noqa: F401  (re-exports)
    pack_rows_int4, quantize_int4_rows)

__all__ = ["Int4Linear", "weight_only_int4", "quantize_int4_rows",
           "pack_rows_int4"]


class Int4Linear(Layer):
    """Weight-only int4 linear: weights stored as PACKED uint8 nibble
    pairs (0.5 B/weight in HBM — the axon backend cannot materialize
    S4 buffers eagerly, so packing is explicit), unpacked + dequantized
    INSIDE the Pallas matmul kernel (ops/int4_matmul.py). A plain XLA
    unpack lowering materializes the bf16 weight copy per call and
    measured 5x SLOWER than bf16 decode — the fused kernel is the
    whole point."""

    def __init__(self, source, group: int = 128):
        super().__init__()
        from ..ops.int4_matmul import pack_rows_int4, quantize_int4_rows
        w = np.asarray(source.weight.numpy())      # [in, out]
        if (w.shape[0] // 2) % group:
            # halves packing needs group | K/2; fall back to a group
            # size that divides (still int4, coarser scaling)
            group = int(np.gcd(w.shape[0] // 2, group))
        q, scale = quantize_int4_rows(w, group)
        self.group = group
        self._in, self._out = w.shape
        self.register_buffer("wq",
                             Tensor(jnp.asarray(pack_rows_int4(q))))
        self.register_buffer("w_scale",
                             Tensor(jnp.asarray(scale, jnp.float32)))
        self.bias = source.bias

    def forward(self, x):
        from ..ops.int4_matmul import int4_matmul
        in_f, out_f = self._in, self._out
        group = self.group

        def f(x, wq, ws, b):
            lead = x.shape[:-1]
            x2 = x.reshape((-1, in_f))
            y = int4_matmul(x2, wq, ws, group=group)
            y = y.reshape(lead + (out_f,))
            if b is not None:
                y = y + b.astype(y.dtype)
            return y.astype(x.dtype)

        args = [x, self.wq, self.w_scale,
                self.bias if self.bias is not None else None]
        if isinstance(x, Tensor):
            # inference-only layer (like the reference's weight-only
            # pass output): the Pallas kernel has no vjp, so the call
            # never records on the tape
            from ..framework.tensor import no_grad
            with no_grad():
                return apply_op(f, *args, _op_name="int4_linear")
        return f(x, getattr(self.wq, "_data", self.wq),
                 getattr(self.w_scale, "_data", self.w_scale),
                 getattr(self.bias, "_data", self.bias)
                 if self.bias is not None else None)


def weight_only_int4(model: Layer, group: int = 128,
                     min_features: int = 256,
                     inplace: bool = True) -> Layer:
    """Swap every big-enough nn.Linear for Int4Linear (see
    weight_only_int8 — same traversal, half the weight bytes)."""
    from ..nn.layer.common import Linear
    from ._swap import swap_layers

    def factory(child):
        if isinstance(child, Linear):
            w = child.weight
            if min(w.shape) >= min_features and \
                    w.shape[0] % group == 0:
                return Int4Linear(child, group)
        return None

    return swap_layers(model, factory, inplace=inplace)
