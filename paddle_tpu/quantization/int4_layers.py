"""Weight-only int4 serving layers: per-group scales, two weights/byte.

Reference analog: the weight_only_quant int4 pass family under
paddle/fluid/inference (analysis_predictor.h int8/int4 story) and
llm.int4-style serving. Decode at small batch is WEIGHT-READ-bound
(benchmarks/RESULTS.md: int8 already converts halved bytes into 1.83x
bs1 tokens/s); int4 halves the bytes again. TPU-native storage is
``jnp.int4`` — XLA packs two nibbles per byte in HBM and the convert
fuses into the consuming dot's operand read — with per-GROUP symmetric
scales along the contraction dim (group ~128) to hold accuracy at
4-bit.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, apply_op
from ..nn.layer_base import Layer

__all__ = ["Int4Linear", "weight_only_int4"]


def quantize_weight_int4(w: np.ndarray, group: int):
    """[in, out] float -> (q int4-valued int8 [in, out],
    scales f32 [n_groups, out]); symmetric, q in [-7, 7]."""
    in_f, out_f = w.shape
    if in_f % group:
        raise ValueError(f"in_features {in_f} % group {group} != 0")
    g = in_f // group
    wg = w.reshape(g, group, out_f).astype(np.float32)
    scale = np.abs(wg).max(axis=1) / 7.0          # [g, out]
    scale = np.where(scale == 0.0, 1.0, scale)
    q = np.clip(np.round(wg / scale[:, None, :]), -7, 7)
    return q.reshape(in_f, out_f).astype(np.int8), scale


class Int4Linear(Layer):
    """Weight-only int4 linear: bf16 activations, int4 weights
    dequantized group-wise on the operand read (no bf16 weight copy
    ever lands in HBM)."""

    def __init__(self, source, group: int = 128):
        super().__init__()
        w = np.asarray(source.weight.numpy())      # [in, out]
        q, scale = quantize_weight_int4(w, group)
        self.group = group
        self._in, self._out = w.shape
        self.register_buffer("wq", Tensor(jnp.asarray(q, jnp.int4)))
        self.register_buffer("w_scale",
                             Tensor(jnp.asarray(scale, jnp.float32)))
        self.bias = source.bias

    def forward(self, x):
        group, in_f, out_f = self.group, self._in, self._out
        g = in_f // group

        def f(x, wq, ws, b):
            # per-group matmul: [..., g, group] x [g, group, out],
            # scales applied to the PARTIAL sums — the int4->bf16
            # convert stays fused into the dot operand, so HBM reads
            # remain 0.5 B/weight
            # bf16 on TPU (MXU dtype); f32 on CPU tests (the CPU
            # backend's DotThunk rejects bf16 x bf16 -> f32)
            cd = jnp.bfloat16 if jax.default_backend() in (
                "tpu", "axon") else jnp.float32
            xg = x.reshape(x.shape[:-1] + (g, group)).astype(cd)
            wg = wq.reshape(g, group, out_f).astype(cd)
            part = jnp.einsum("...gk,gko->...go", xg, wg,
                              preferred_element_type=jnp.float32)
            y = jnp.sum(part * ws, axis=-2)     # ws [g, out] broadcasts
            if b is not None:
                y = y + b.astype(jnp.float32)
            return y.astype(x.dtype)

        args = [x, self.wq, self.w_scale,
                self.bias if self.bias is not None else None]
        if isinstance(x, Tensor):
            return apply_op(f, *args, _op_name="int4_linear")
        return f(x, getattr(self.wq, "_data", self.wq),
                 getattr(self.w_scale, "_data", self.w_scale),
                 getattr(self.bias, "_data", self.bias)
                 if self.bias is not None else None)


def weight_only_int4(model: Layer, group: int = 128,
                     min_features: int = 256,
                     inplace: bool = True) -> Layer:
    """Swap every big-enough nn.Linear for Int4Linear (see
    weight_only_int8 — same traversal, half the weight bytes)."""
    from ..nn.layer.common import Linear
    from ._swap import swap_layers

    def factory(child):
        if isinstance(child, Linear):
            w = child.weight
            if min(w.shape) >= min_features and \
                    w.shape[0] % group == 0:
                return Int4Linear(child, group)
        return None

    return swap_layers(model, factory, inplace=inplace)
