"""Quantized layer wrappers (reference:
/root/reference/python/paddle/quantization/wrapper.py ObserveWrapper;
paddle/nn/quant/qat/linear.py QuantedLinear-style layers)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, apply_op
from ..nn.layer_base import Layer


def quant_dequant(x, absmax, bits: int = 8):
    """Symmetric quantize→dequantize with straight-through gradient.
    ``absmax`` may be a python float (per-tensor) or a broadcastable array
    (per-channel, keepdims layout)."""
    qmax = float(2 ** (bits - 1) - 1)
    scale = absmax / qmax

    def f(a):
        q = jnp.clip(jnp.round(a / scale), -qmax - 1, qmax)
        return a + jax.lax.stop_gradient(q * scale - a)

    if isinstance(x, Tensor):
        return apply_op(f, x, _op_name="quant_dequant")
    return f(jnp.asarray(x))


def _qdq_dynamic(x, bits: int = 8):
    """qdq with absmax computed in-graph (jit-safe uncalibrated path)."""
    qmax = float(2 ** (bits - 1) - 1)

    def f(a):
        scale = jnp.maximum(jnp.max(jnp.abs(a)), 1e-8) / qmax
        q = jnp.clip(jnp.round(a / scale), -qmax - 1, qmax)
        return a + jax.lax.stop_gradient(q * scale - a)

    if isinstance(x, Tensor):
        return apply_op(f, x, _op_name="quant_dequant_dynamic")
    return f(jnp.asarray(x))


class ObserveWrapper(Layer):
    """Wrap a layer with activation observers on input/output
    (wrapper.py:24)."""

    def __init__(self, observer, observed, observe_input=True,
                 observe_output=False):
        super().__init__()
        self._observer = observer
        self._observed = observed
        self._in = observe_input
        self._out = observe_output

    @property
    def observed(self):
        return self._observed

    @property
    def observer(self):
        return self._observer

    def forward(self, *args, **kwargs):
        if self._in and args:
            args = (self._observer(args[0]),) + args[1:]
        out = self._observed(*args, **kwargs)
        if self._out:
            out = self._observer(out)
        return out


class _QuantedOpLayer(Layer):
    """QAT wrapper: fake-quant the weight (per-channel) and the input
    activation (per-tensor EMA) around the wrapped layer's op."""

    def __init__(self, source, q_config):
        super().__init__()
        self._source = source
        if q_config.weight is not None:
            self.weight_quanter = q_config.weight._instance()
        else:
            self.weight_quanter = None
        if q_config.activation is not None:
            self.activation_quanter = q_config.activation._instance()
        else:
            self.activation_quanter = None

    @property
    def weight(self):
        return self._source.weight

    @property
    def bias(self):
        return getattr(self._source, "bias", None)

    def _quanted_weight(self):
        w = self._source.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return w

    def _quanted_input(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        return x


class QuantedLinear(_QuantedOpLayer):
    def forward(self, x):
        from ..nn import functional as F
        return F.linear(self._quanted_input(x), self._quanted_weight(),
                        self.bias)


class QuantedConv2D(_QuantedOpLayer):
    def forward(self, x):
        from ..nn import functional as F
        src = self._source
        return F.conv2d(self._quanted_input(x), self._quanted_weight(),
                        src.bias, src._stride, src._padding, src._dilation,
                        src._groups, src._data_format)
