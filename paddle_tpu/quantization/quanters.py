"""Fake quanters for QAT (reference:
/root/reference/python/paddle/quantization/quanters/abs_max.py
FakeQuanterWithAbsMaxObserver — EMA absmax + fake quantize).

Straight-through estimator: out = x + stop_grad(qdq(x) - x). Identity
gradient, quantized forward, all inside one XLA graph. Calibration state
lives in registered buffers so it survives paddle.save/load.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, apply_op
from ..nn.layer_base import Layer
from .wrapper import quant_dequant, _qdq_dynamic


def _is_traced(arr) -> bool:
    return isinstance(arr, jax.core.Tracer)


class BaseQuanter(Layer):
    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return -1

    def zero_points(self):
        return 0.0


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """Per-tensor EMA absmax fake quantizer (quanters/abs_max.py:63:
    moving-average absmax state updated each training step).

    Under jit tracing the EMA update is skipped: the frozen buffered scale
    is used if calibrated, else the absmax is computed in-graph
    (dynamic-range qdq) — both jit-safe.
    """

    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 dtype=None, name=None):
        super().__init__()
        self._moving_rate = moving_rate
        self._quant_bits = bit_length
        self.register_buffer("_scale_state",
                             Tensor(jnp.zeros((), jnp.float32)))
        self.register_buffer("_inited", Tensor(jnp.zeros((), jnp.bool_)))

    def _state(self):
        return float(np.asarray(self._buffers["_scale_state"]._data))

    def _is_inited(self):
        return bool(np.asarray(self._buffers["_inited"]._data))

    def scales(self):
        qmax = float(2 ** (self._quant_bits - 1) - 1)
        return max(self._state(), 1e-8) / qmax

    def forward(self, x):
        arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        if _is_traced(arr):
            if self._is_inited():
                return quant_dequant(x, max(self._state(), 1e-8),
                                     self._quant_bits)
            return _qdq_dynamic(x, self._quant_bits)
        cur = float(jnp.max(jnp.abs(arr)))
        if self.training:
            if not self._is_inited():
                new = cur
                self._buffers["_inited"] = Tensor(
                    jnp.ones((), jnp.bool_))
            else:
                r = self._moving_rate
                new = r * self._state() + (1 - r) * cur
            self._buffers["_scale_state"] = Tensor(
                jnp.asarray(new, jnp.float32))
        absmax = max(self._state() if self._is_inited() else cur, 1e-8)
        return quant_dequant(x, absmax, self._quant_bits)


class FakeQuanterChannelWiseAbsMaxObserver(BaseQuanter):
    """Per-channel absmax fake quantizer for weights (quanters/abs_max.py
    channel-wise variant; quant_axis 0 = output channels). The per-channel
    absmax is recomputed from the tensor each call (weights are live
    during QAT); the last concrete absmax is kept for scales() export."""

    def __init__(self, quant_axis: int = 0, bit_length: int = 8,
                 dtype=None, name=None):
        super().__init__()
        self._axis = quant_axis
        self._quant_bits = bit_length
        # shape depends on the wrapped weight → not persistable
        self.register_buffer("_last_absmax", None, persistable=False)

    def quant_axis(self):
        return self._axis

    def scales(self):
        qmax = float(2 ** (self._quant_bits - 1) - 1)
        last = self._buffers.get("_last_absmax")
        if last is None:
            return None
        return np.asarray(last._data) / qmax

    def forward(self, x):
        axis = self._axis
        arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        red = tuple(i for i in range(arr.ndim) if i != axis)
        absmax = jnp.maximum(jnp.max(jnp.abs(arr), axis=red,
                                     keepdims=True), 1e-8)
        if not _is_traced(arr):
            self._buffers["_last_absmax"] = Tensor(absmax)
        return quant_dequant(x, absmax, self._quant_bits)
