"""Observers: collect activation/weight ranges during calibration
(reference: /root/reference/python/paddle/quantization/observers/abs_max.py
AbsmaxObserver; base_observer.py BaseObserver)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..nn.layer_base import Layer


class BaseObserver(Layer):
    """Identity layer that records quantization statistics on forward."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self._quant_bits = quant_bits

    def bit_length(self) -> int:
        return self._quant_bits

    def quant_axis(self):
        return -1  # per-tensor

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return 0.0

    def forward(self, x):
        self._observe(x)
        return x

    def _observe(self, x):
        raise NotImplementedError


class AbsmaxObserver(BaseObserver):
    """Per-tensor abs-max range observer (observers/abs_max.py:30).
    State is a registered buffer → survives paddle.save/load."""

    def __init__(self, quant_bits: int = 8):
        super().__init__(quant_bits)
        self.register_buffer("_stat_max", Tensor(jnp.zeros((),
                                                           jnp.float32)))

    @property
    def _max(self):
        return float(np.asarray(self._buffers["_stat_max"]._data))

    def _observe(self, x):
        cur = float(jnp.max(jnp.abs(x._data)) if isinstance(x, Tensor)
                    else np.abs(x).max())
        self._buffers["_stat_max"] = Tensor(
            jnp.asarray(max(self._max, cur), jnp.float32))

    def scales(self):
        qmax = float(2 ** (self._quant_bits - 1) - 1)
        return max(self._max, 1e-8) / qmax

    def cal_thresholds(self):
        return self._max


class AVGObserver(BaseObserver):
    """Average-of-batch-absmax observer (imperative PTQ's 'avg' strategy,
    reference python/paddle/quantization/imperative/ptq_quantizer.py)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__(quant_bits)
        self.register_buffer("_stat_sum", Tensor(jnp.zeros((),
                                                           jnp.float32)))
        self.register_buffer("_stat_n", Tensor(jnp.zeros((), jnp.int32)))

    def _observe(self, x):
        arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        s = float(np.asarray(self._buffers["_stat_sum"]._data))
        n = int(np.asarray(self._buffers["_stat_n"]._data))
        self._buffers["_stat_sum"] = Tensor(
            jnp.asarray(s + float(jnp.max(jnp.abs(arr))), jnp.float32))
        self._buffers["_stat_n"] = Tensor(jnp.asarray(n + 1, jnp.int32))

    def scales(self):
        qmax = float(2 ** (self._quant_bits - 1) - 1)
        s = float(np.asarray(self._buffers["_stat_sum"]._data))
        n = int(np.asarray(self._buffers["_stat_n"]._data))
        return max(s / max(n, 1), 1e-8) / qmax
