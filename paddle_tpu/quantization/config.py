"""QuantConfig (reference:
/root/reference/python/paddle/quantization/config.py:67 — per-layer /
per-name / per-type quantizer configuration with priority
layer > name > type, plus factory.py QuanterFactory)."""
from __future__ import annotations

from typing import Optional

from ..nn.layer_base import Layer


class QuanterFactory:
    """Lazily-constructed quanter/observer spec (factory.py:28)."""

    def __init__(self, cls, *args, **kwargs):
        self._cls = cls
        self._args = args
        self._kwargs = kwargs

    def _instance(self):
        return self._cls(*self._args, **self._kwargs)


def _as_factory(q):
    if q is None or isinstance(q, QuanterFactory):
        return q
    if isinstance(q, type):
        return QuanterFactory(q)
    raise TypeError(f"expected QuanterFactory or class, got {type(q)}")


class SingleLayerConfig:
    """Quanter pair for one layer (config.py:40)."""

    def __init__(self, activation=None, weight=None):
        self._activation = _as_factory(activation)
        self._weight = _as_factory(weight)

    @property
    def activation(self) -> Optional[QuanterFactory]:
        return self._activation

    @property
    def weight(self) -> Optional[QuanterFactory]:
        return self._weight

    def __str__(self):
        return f"activation: {self._activation}\nweight: {self._weight}"


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        if activation is None and weight is None:
            self._global_config = None
        else:
            self._global_config = SingleLayerConfig(activation, weight)
        self._layer2config = {}
        self._prefix2config = {}
        self._type2config = {}
        self._qat_layer_mapping = _default_mapping()
        self._customized_leaves = []

    # -- registration (priority: layer > name > type > global) ------------
    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, list) else [layer]
        cfg = SingleLayerConfig(activation, weight)
        for l in layers:
            self._layer2config[id(l)] = cfg

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = layer_name if isinstance(layer_name, list) else [layer_name]
        cfg = SingleLayerConfig(activation, weight)
        for n in names:
            self._prefix2config[n] = cfg

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, list) else [layer_type]
        cfg = SingleLayerConfig(activation, weight)
        for t in types:
            self._type2config[t] = cfg

    def add_qat_layer_mapping(self, source_type, target_type):
        self._qat_layer_mapping[source_type] = target_type

    def add_customized_leaf(self, layer_type):
        self._customized_leaves.append(layer_type)

    @property
    def customized_leaves(self):
        return self._customized_leaves

    @property
    def qat_layer_mappings(self):
        return self._qat_layer_mapping

    @property
    def default_qat_layer_mapping(self):
        return _default_mapping()

    @property
    def global_config(self):
        return self._global_config

    # -- lookup -----------------------------------------------------------
    def _get_config_by_layer(self, layer: Layer, name: str = ""):
        if id(layer) in self._layer2config:
            return self._layer2config[id(layer)]
        for prefix, cfg in self._prefix2config.items():
            if name == prefix or name.startswith(prefix + "."):
                return cfg
        for t, cfg in self._type2config.items():
            if isinstance(layer, t):
                return cfg
        return self._global_config

    def _is_quantifiable(self, layer: Layer) -> bool:
        return type(layer) in self._qat_layer_mapping


def _default_mapping():
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import Conv2D
    from .wrapper import QuantedConv2D, QuantedLinear
    return {Linear: QuantedLinear, Conv2D: QuantedConv2D}
