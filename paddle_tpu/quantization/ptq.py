"""PTQ — post-training quantization (reference:
/root/reference/python/paddle/quantization/ptq.py:29 — insert observers,
calibrate with forward passes, convert to a quantized model)."""
from __future__ import annotations

import copy

from ..nn.layer_base import Layer
from .config import QuantConfig
from .qat import Quantization
from .wrapper import ObserveWrapper, quant_dequant


class _CalibratedLayer(Layer):
    """Deploy-time layer: qdq input with the calibrated scale, then run
    the original layer (whose weights were qdq'd in-place at convert)."""

    def __init__(self, source: Layer, act_absmax, bits):
        super().__init__()
        self._source = source
        self._absmax = act_absmax
        self._bits = bits

    def forward(self, x):
        if self._absmax is not None:
            x = quant_dequant(x, self._absmax, self._bits)
        return self._source(x)


class PTQ(Quantization):
    def __init__(self, config: QuantConfig):
        super().__init__(config)

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        """Insert activation observers in front of quantifiable layers."""
        if not inplace:
            model = copy.deepcopy(model)
        model.eval()
        self._walk(model, "")
        return model

    def _walk(self, layer: Layer, prefix: str):
        for name, child in list(layer.named_children()):
            full = f"{prefix}.{name}" if prefix else name
            cfg = self._config._get_config_by_layer(child, full)
            if cfg is not None and cfg.activation is not None and \
                    self._config._is_quantifiable(child):
                obs = cfg.activation._instance()
                wrapper = ObserveWrapper(obs, child)
                wrapper._weight_factory = cfg.weight
                layer.add_sublayer(name, wrapper)
            else:
                self._walk(child, full)

    def convert(self, model: Layer, inplace: bool = False,
                remove_quanter: bool = True, real: bool = False) -> Layer:
        """Replace observers with deploy-time layers.

        ``real=False`` (reference parity): fixed-scale qdq simulation.
        ``real=True``: swap observed Linear/Conv2D for REAL int8 layers
        (quantization/int8_layers.py) executing on the int8 MXU —
        weights stored int8 per-channel, activations quantized with the
        calibrated static scale. Layers without an int8 kernel keep the
        qdq fallback. ``to_static``/``jit.save`` after this exports an
        int8 program the inference Predictor serves as-is.
        """
        if not inplace:
            model = copy.deepcopy(model)
        self._convert_walk(model, real)
        model.eval()
        return model

    def _convert_walk(self, layer: Layer, real: bool = False):
        for name, child in list(layer.named_children()):
            if isinstance(child, ObserveWrapper):
                obs = child.observer
                qmax = float(2 ** (obs.bit_length() - 1) - 1)
                absmax = obs.scales() * qmax
                source = child.observed
                if real and obs.bit_length() == 8:
                    from .int8_layers import realize_int8
                    int8 = realize_int8(source, absmax)
                    if int8 is not None:
                        layer.add_sublayer(name, int8)
                        continue
                wf = getattr(child, "_weight_factory", None)
                if wf is not None and getattr(source, "weight", None) \
                        is not None:
                    # weights are static post-training: bake the qdq into
                    # the param (per the configured weight quanter)
                    wq = wf._instance()
                    source.weight.set_value(
                        wq(source.weight).detach())
                layer.add_sublayer(
                    name, _CalibratedLayer(source, absmax,
                                           obs.bit_length()))
            else:
                self._convert_walk(child, real)
