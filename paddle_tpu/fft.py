"""Discrete Fourier transforms (reference: python/paddle/fft.py — the
reference backs these with pocketfft/cuFFT kernels, phi/kernels/fft_*;
here XLA's native FFT HLO does the work via jnp.fft, so the whole module
is thin dispatch with paddle argument conventions).

Norm convention matches the reference: "backward" (default), "ortho",
"forward".
"""
from __future__ import annotations

import jax.numpy as jnp

from .framework.tensor import Tensor, apply_op

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")


def _check_norm(norm):
    if norm is None:
        return "backward"
    if norm not in _NORMS:
        raise ValueError(
            f"norm should be one of {_NORMS}, but got {norm!r}")
    return norm


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _fft1(fn, name, x, n, axis, norm):
    norm = _check_norm(norm)
    return apply_op(lambda a: fn(a, n=n, axis=axis, norm=norm), _t(x),
                    _op_name=name)


def _fftn(fn, name, x, s, axes, norm):
    norm = _check_norm(norm)
    return apply_op(lambda a: fn(a, s=s, axes=axes, norm=norm), _t(x),
                    _op_name=name)


def fft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft1(jnp.fft.fft, "fft", x, n, axis, norm)


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft1(jnp.fft.ifft, "ifft", x, n, axis, norm)


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft1(jnp.fft.rfft, "rfft", x, n, axis, norm)


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft1(jnp.fft.irfft, "irfft", x, n, axis, norm)


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft1(jnp.fft.hfft, "hfft", x, n, axis, norm)


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft1(jnp.fft.ihfft, "ihfft", x, n, axis, norm)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _fftn(jnp.fft.fft2, "fft2", x, s, axes, norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _fftn(jnp.fft.ifft2, "ifft2", x, s, axes, norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _fftn(jnp.fft.rfft2, "rfft2", x, s, axes, norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _fftn(jnp.fft.irfft2, "irfft2", x, s, axes, norm)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    # jnp.fft has no hfft2; compose: fft on the leading transform axis then
    # hfft over the last (verified against scipy.fft.hfft2 for all norms).
    norm = _check_norm(norm)

    def _h2(a):
        n0 = None if s is None else s[0]
        n1 = None if s is None else s[1]
        a = jnp.fft.fft(a, n=n0, axis=axes[0], norm=norm)
        return jnp.fft.hfft(a, n=n1, axis=axes[1], norm=norm)

    return apply_op(_h2, _t(x), _op_name="hfft2")


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    norm = _check_norm(norm)

    def _ih2(a):
        n0 = None if s is None else s[0]
        n1 = None if s is None else s[1]
        a = jnp.fft.ihfft(a, n=n1, axis=axes[1], norm=norm)
        return jnp.fft.ifft(a, n=n0, axis=axes[0], norm=norm)

    return apply_op(_ih2, _t(x), _op_name="ihfft2")


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return _fftn(jnp.fft.fftn, "fftn", x, s, axes, norm)


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return _fftn(jnp.fft.ifftn, "ifftn", x, s, axes, norm)


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return _fftn(jnp.fft.rfftn, "rfftn", x, s, axes, norm)


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return _fftn(jnp.fft.irfftn, "irfftn", x, s, axes, norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    if axes is None:
        axes = tuple(range(_t(x).ndim))
    norm = _check_norm(norm)

    def _hn(a):
        ss = s or [None] * len(axes)
        for ax, n in zip(axes[:-1], ss[:-1]):
            a = jnp.fft.fft(a, n=n, axis=ax, norm=norm)
        return jnp.fft.hfft(a, n=ss[-1], axis=axes[-1], norm=norm)

    return apply_op(_hn, _t(x), _op_name="hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    if axes is None:
        axes = tuple(range(_t(x).ndim))
    norm = _check_norm(norm)

    def _ihn(a):
        ss = s or [None] * len(axes)
        a = jnp.fft.ihfft(a, n=ss[-1], axis=axes[-1], norm=norm)
        for ax, n in zip(reversed(axes[:-1]), reversed(ss[:-1])):
            a = jnp.fft.ifft(a, n=n, axis=ax, norm=norm)
        return a

    return apply_op(_ihn, _t(x), _op_name="ihfftn")


def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(n, d=d)
    if dtype is not None:
        from .framework.dtype import to_dtype
        out = out.astype(to_dtype(dtype))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(n, d=d)
    if dtype is not None:
        from .framework.dtype import to_dtype
        out = out.astype(to_dtype(dtype))
    return Tensor(out)


def fftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.fftshift(a, axes=axes), _t(x),
                    _op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.ifftshift(a, axes=axes), _t(x),
                    _op_name="ifftshift")
