"""Sparse tensor creation (reference:
/root/reference/python/paddle/sparse/creation.py — sparse_coo_tensor:
creation.py:54, sparse_csr_tensor:~160)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from .tensor import SparseCooTensor, SparseCsrTensor


def _infer_dense_shape(indices, values):
    idx = np.asarray(indices)
    vals_shape = values.shape if hasattr(values, "shape") else \
        np.asarray(values).shape
    sparse_shape = [int(idx[d].max()) + 1 if idx.shape[1] else 0
                    for d in range(idx.shape[0])]
    return tuple(sparse_shape) + tuple(int(s) for s in vals_shape[1:])


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """Build a COO tensor from [sparse_ndim, nnz] indices + values."""
    idx = indices._data if isinstance(indices, Tensor) else \
        jnp.asarray(indices)
    if shape is None:
        shape = _infer_dense_shape(np.asarray(idx), values)
    t = SparseCooTensor(idx, values if not dtype
                        else Tensor(jnp.asarray(
                            values._data if isinstance(values, Tensor)
                            else values)).astype(dtype),
                        shape)
    t.values().stop_gradient = stop_gradient
    return t


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    """Build a CSR tensor from compressed rows / cols / values."""
    t = SparseCsrTensor(crows, cols, values, shape)
    if dtype is not None:
        t = t.astype(dtype)
    t.values().stop_gradient = stop_gradient
    return t


def _coo_to_csr(coo: SparseCooTensor) -> SparseCsrTensor:
    if coo.sparse_ndim not in (2, 3):
        raise ValueError("CSR needs 2-D or batched 3-D sparse dims")
    idx = np.asarray(coo._indices)
    shape = coo._shape
    if coo.sparse_ndim == 2:
        order = np.lexsort((idx[1], idx[0]))
        rows, cols = idx[0][order], idx[1][order]
        crows = np.zeros(shape[0] + 1, dtype=np.int32)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows).astype(np.int32)
        vals = coo.values()
        from ..framework.tensor import apply_op
        ord_arr = jnp.asarray(order)
        vals = apply_op(lambda v: v[ord_arr], vals, _op_name="coo_sort")
        return SparseCsrTensor(crows, cols, vals, shape)
    raise NotImplementedError("batched COO→CSR: convert per batch")


def to_sparse_coo(dense: Tensor, sparse_dim: int) -> SparseCooTensor:
    """Dense→COO. Nonzero pattern is computed on host (data-dependent
    shape — outside jit by design, like the reference's dense_to_coo
    kernel paddle/phi/kernels/sparse/sparse_utils_kernel.h)."""
    arr = np.asarray(dense.numpy())
    red = tuple(range(sparse_dim, arr.ndim))
    mask = (arr != 0).any(axis=red) if red else (arr != 0)
    idx = np.stack(np.nonzero(mask)).astype(np.int32)
    from ..framework.tensor import apply_op
    idx_t = tuple(jnp.asarray(i) for i in idx)
    vals = apply_op(lambda d: d[idx_t], dense, _op_name="dense_to_coo")
    return SparseCooTensor(idx, vals, arr.shape, coalesced=True)


def to_sparse_csr(dense: Tensor) -> SparseCsrTensor:
    return _coo_to_csr(to_sparse_coo(dense, 2))
