"""paddle_tpu.sparse — COO/CSR sparse tensors + ops + nn (reference:
/root/reference/python/paddle/sparse/__init__.py)."""
from .tensor import SparseCooTensor, SparseCsrTensor, is_sparse  # noqa: F401
from .creation import (  # noqa: F401
    sparse_coo_tensor, sparse_csr_tensor, to_sparse_coo, to_sparse_csr)
from .ops import (  # noqa: F401
    abs, add, addmm, asin, asinh, atan, atanh, cast, coalesce, deg2rad,
    divide, expm1, is_same_shape, isnan, leaky_relu, log1p, mask_as,
    masked_matmul, matmul, multiply, mv, neg, pow, rad2deg, relu, relu6,
    reshape, sin, sinh, slice, sqrt, square, subtract, sum, tan, tanh,
    transpose, pca_lowrank)
from . import nn  # noqa: F401

# Dense-Tensor conversion methods (paddle exposes these on Tensor:
# /root/reference/python/paddle/sparse/creation.py + pybind eager_method)
from ..framework.tensor import Tensor as _Tensor

_Tensor.to_sparse_coo = lambda self, sparse_dim=None: to_sparse_coo(
    self, sparse_dim if sparse_dim is not None else len(self.shape))
_Tensor.to_sparse_csr = lambda self: to_sparse_csr(self)
_Tensor.is_sparse_coo = lambda self: False
_Tensor.is_sparse_csr = lambda self: False

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "is_sparse",
    "sparse_coo_tensor", "sparse_csr_tensor",
    "abs", "add", "addmm", "asin", "asinh", "atan", "atanh", "cast",
    "coalesce", "deg2rad", "divide", "expm1", "is_same_shape", "isnan",
    "leaky_relu", "log1p", "mask_as", "masked_matmul", "matmul",
    "multiply", "mv", "neg", "pow", "rad2deg", "relu", "relu6", "reshape",
    "sin", "sinh", "sqrt", "square", "subtract", "sum", "tan", "tanh",
    "transpose", "nn", "slice", "pca_lowrank",
]
