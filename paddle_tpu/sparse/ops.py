"""Sparse unary/binary/matmul ops (reference:
/root/reference/python/paddle/sparse/unary.py, binary.py, multiary.py).

All ops lower to gathers, scatter-adds and segment reductions on the dense
component arrays — the XLA-friendly formulation; there are no per-format
hand kernels (the reference has ~100 under paddle/phi/kernels/sparse/).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor, apply_op
from .tensor import SparseCooTensor, SparseCsrTensor, is_sparse


def _map_values(x, fn, name):
    """Apply a zero-preserving elementwise fn to the values array."""
    out_vals = apply_op(fn, x.values(), _op_name=name)
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x._indices, out_vals, x._shape, x._coalesced)
    return SparseCsrTensor(x._crows, x._cols, out_vals, x._shape)


# -- unary (zero-preserving) ----------------------------------------------

def _unary(name, fn):
    def op(x, *args, **kwargs):
        return _map_values(x, lambda v: fn(v, *args, **kwargs),
                           f"sparse_{name}")
    op.__name__ = name
    return op


sin = _unary("sin", jnp.sin)
sinh = _unary("sinh", jnp.sinh)
tan = _unary("tan", jnp.tan)
tanh = _unary("tanh", jnp.tanh)
asin = _unary("asin", jnp.arcsin)
asinh = _unary("asinh", jnp.arcsinh)
atan = _unary("atan", jnp.arctan)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)
expm1 = _unary("expm1", jnp.expm1)
log1p = _unary("log1p", jnp.log1p)
relu = _unary("relu", lambda v: jnp.maximum(v, 0))
relu6 = _unary("relu6", lambda v: jnp.clip(v, 0, 6))
leaky_relu = _unary("leaky_relu",
                    lambda v, negative_slope=0.01:
                    jnp.where(v >= 0, v, v * negative_slope))
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
pow = _unary("pow", lambda v, factor: jnp.power(v, factor))


def cast(x, index_dtype=None, value_dtype=None):
    """Cast values and/or index dtype. Note: without jax x64 mode int64
    indices are stored as int32 (JAX platform constraint)."""
    out = x.astype(value_dtype) if value_dtype is not None else x
    if index_dtype is not None:
        idt = jnp.dtype(str(index_dtype)) if not hasattr(
            index_dtype, "name") else jnp.dtype(index_dtype.name)
        if isinstance(out, SparseCooTensor):
            out = SparseCooTensor(out._indices, out._values, out._shape,
                                  coalesced=out._coalesced)
            out._indices = out._indices.astype(idt)
        else:
            out = SparseCsrTensor(out._crows, out._cols, out._values,
                                  out._shape)
            out._crows = out._crows.astype(idt)
            out._cols = out._cols.astype(idt)
    return out


def isnan(x):
    return _map_values(x, jnp.isnan, "sparse_isnan")


def coalesce(x):
    return x.coalesce()


def reshape(x, shape):
    dense = x.to_dense()
    out = apply_op(lambda d: d.reshape(shape), dense, _op_name="sp_reshape")
    from .creation import to_sparse_coo
    return to_sparse_coo(out, len(shape))


def transpose(x, perm):
    return x.transpose(perm)


def sum(x, axis=None, dtype=None, keepdim=False):
    vals = x.values()
    if axis is None:
        return apply_op(lambda v: v.sum(), vals, _op_name="sparse_sum")
    dense = x.to_dense()
    return apply_op(lambda d: d.sum(axis=axis, keepdims=keepdim), dense,
                    _op_name="sparse_sum")


# -- binary ----------------------------------------------------------------

def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def _union_coo(x: SparseCooTensor, y: SparseCooTensor, combine, name):
    """Elementwise op over the union pattern: concat indices, combine via
    coalesce's segment-sum."""
    cx, cy = x.coalesce(), y.coalesce()
    idx = jnp.concatenate([cx._indices, cy._indices], axis=1)
    vx, vy = cx.values(), cy.values()
    vals = apply_op(lambda a, b: jnp.concatenate([a, combine(b)]), vx, vy,
                    _op_name=name)
    return SparseCooTensor(idx, vals, x._shape).coalesce()


def _to_coo(t) -> SparseCooTensor:
    return t.to_sparse_coo() if isinstance(t, SparseCsrTensor) else t


def add(x, y, name=None):
    if is_sparse(x) and is_sparse(y):
        out = _union_coo(_to_coo(x), _to_coo(y), lambda b: b, "sparse_add")
        return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) \
            else out
    if is_sparse(x) and isinstance(y, Tensor):
        return apply_op(lambda d, s: d + s, y, x.to_dense(),
                        _op_name="sparse_dense_add")
    raise TypeError("sparse.add expects sparse operands")


def subtract(x, y, name=None):
    out = _union_coo(_to_coo(x), _to_coo(y), lambda b: -b,
                     "sparse_subtract")
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def _intersect_dense(x, y, fn, name):
    """Ops whose support is the intersection pattern — computed by
    gathering both dense views at x's pattern (correct because the result
    is zero wherever either operand is zero)."""
    cx = x.coalesce() if isinstance(x, SparseCooTensor) else \
        x.to_sparse_coo()
    yd = y.to_dense() if is_sparse(y) else y
    idx = tuple(cx._indices)
    vals = apply_op(lambda v, d: fn(v, d[idx]), cx.values(), yd,
                    _op_name=name)
    out = SparseCooTensor(cx._indices, vals, cx._shape, coalesced=True)
    if isinstance(x, SparseCsrTensor):
        return out.to_sparse_csr()
    return out


def multiply(x, y, name=None):
    return _intersect_dense(x, y, lambda v, d: v * d, "sparse_multiply")


def divide(x, y, name=None):
    return _intersect_dense(x, y, lambda v, d: v / d, "sparse_divide")


def mask_as(x: Tensor, mask, name=None):
    """Take dense ``x``'s entries at ``mask``'s sparsity pattern
    (reference: paddle.sparse.mask_as)."""
    cm = _to_coo(mask).coalesce()
    idx = tuple(cm._indices)
    vals = apply_op(lambda d: d[idx], x, _op_name="sparse_mask_as")
    out = SparseCooTensor(cm._indices, vals, cm._shape, coalesced=True)
    if isinstance(mask, SparseCsrTensor):
        return out.to_sparse_csr()
    return out


# -- matmul ----------------------------------------------------------------

def matmul(x, y, name=None):
    """sparse @ dense → dense. COO formulation: gather dense rows at col
    indices, scale by values, scatter-add into output rows — one fused
    gather/scatter XLA graph (vs cuSPARSE SpMM in the reference,
    paddle/phi/kernels/sparse/gpu/matmul_kernel.cu)."""
    if not is_sparse(x):
        raise TypeError("matmul expects sparse lhs")
    coo = x if isinstance(x, SparseCooTensor) else x.to_sparse_coo()
    coo = coo.coalesce()
    if coo.sparse_ndim != 2:
        raise NotImplementedError("sparse matmul: 2-D lhs only")
    rows, cols = coo._indices[0], coo._indices[1]
    n = coo._shape[0]
    yd = y if isinstance(y, Tensor) else Tensor(jnp.asarray(y))

    def f(vals, dense):
        gathered = dense[cols] * vals[:, None]
        out = jnp.zeros((n, dense.shape[1]), dtype=gathered.dtype)
        return out.at[rows].add(gathered)

    return apply_op(f, coo.values(), yd, _op_name="sparse_matmul")


def mv(x, vec, name=None):
    out = matmul(x, apply_op(lambda v: v[:, None], vec, _op_name="expand"))
    return apply_op(lambda o: o[:, 0], out, _op_name="squeeze")


def masked_matmul(x: Tensor, y: Tensor, mask, name=None):
    """(x @ y) sampled at mask's pattern (SDDMM). Row/col gather + dot —
    never materializes the dense product."""
    coo = _to_coo(mask).coalesce()
    rows, cols = coo._indices[0], coo._indices[1]

    def f(a, b):
        return (a[rows] * b[:, cols].T).sum(-1)

    vals = apply_op(f, x, y, _op_name="masked_matmul")
    out = SparseCooTensor(coo._indices, vals, coo._shape, coalesced=True)
    if isinstance(mask, SparseCsrTensor):
        return out.to_sparse_csr()
    return out


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    prod = matmul(x, y)
    dense_in = input.to_dense() if is_sparse(input) else input
    return apply_op(lambda i, p: beta * i + alpha * p, dense_in, prod,
                    _op_name="sparse_addmm")


def slice(x, axes, starts, ends, name=None):
    """Slice a sparse tensor (reference sparse/unary.py slice): computed
    on the dense view and re-sparsified (XLA fuses the scatter/gather;
    there is no CUDA slice kernel to mirror)."""
    from .creation import sparse_coo_tensor
    dense = x.to_dense() if is_sparse(x) else x
    from ..ops.manipulation import slice as dense_slice
    out = dense_slice(dense, axes, starts, ends)
    if not is_sparse(x):
        return out
    # to_sparse_coo routes the value gather through apply_op, so
    # gradients flow to the sliced values (a raw numpy round-trip
    # would silently detach them)
    from .creation import to_sparse_coo
    return to_sparse_coo(out, len(out.shape))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA over the dense view (reference sparse pca_lowrank)."""
    from ..ops.linalg import pca_lowrank as dense_pca
    dense = x.to_dense() if is_sparse(x) else x
    return dense_pca(dense, q=q, center=center, niter=niter)
