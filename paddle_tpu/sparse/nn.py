"""sparse.nn — layers over sparse tensors (reference:
/root/reference/python/paddle/sparse/nn/__init__.py: ReLU/ReLU6/LeakyReLU/
Softmax/BatchNorm/SyncBatchNorm/Conv2D/Conv3D/SubmConv2D/SubmConv3D/
MaxPool3D).

TPU-first notes:
- activations/norm run on the dense ``values`` array only.
- Softmax is a per-CSR-row segment softmax (segment-max/segment-sum) —
  the reference's csr softmax kernel
  (paddle/phi/kernels/sparse/gpu/softmax_kernel.cu) done with XLA segment
  ops.
- SubmConv2D/3D (stride 1, groups 1 — the LiDAR/point-cloud hot path)
  run a REAL sparse conv: host-built rulebook + device gather/GEMM/
  scatter (sparse/rulebook.py; reference conv_kernel.cu + conv.cu.h).
  Compute scales with nnz, not voxel volume.
- Strided/grouped sparse convs lower to dense XLA conv on
  ``to_dense()`` then re-sparsify: with stride the output support is
  the kernel-reachable set (data-dependent size — a host round trip
  anyway), and the MXU makes dense conv competitive at moderate
  densities.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, apply_op
from ..nn.layer_base import Layer
from ..nn import functional as F_dense
from . import ops as sp_ops
from .tensor import SparseCooTensor, SparseCsrTensor
from .creation import to_sparse_coo


class ReLU(Layer):
    def forward(self, x):
        return sp_ops.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return sp_ops.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return sp_ops.leaky_relu(x, negative_slope=self._slope)


def softmax(x, axis=-1, name=None):
    """Sparse softmax over the last dim of a CSR matrix (per-row over
    stored values) or COO last-sparse-dim."""
    if axis != -1:
        raise NotImplementedError("sparse softmax: axis=-1 only")
    csr = x if isinstance(x, SparseCsrTensor) else None
    coo = x.to_sparse_coo() if csr is not None else x.coalesce()
    rows = coo._indices[:-1]
    sparse_shape = coo._shape[:coo.sparse_ndim - 1]
    lin = jnp.zeros(coo.nnz(), dtype=jnp.int32)
    for d, s in enumerate(sparse_shape):
        lin = lin * s + rows[d]
    n_seg = 1
    for s in sparse_shape:
        n_seg *= s

    def f(vals):
        mx = jax.ops.segment_max(vals, lin, num_segments=n_seg)
        e = jnp.exp(vals - mx[lin])
        z = jax.ops.segment_sum(e, lin, num_segments=n_seg)
        return e / z[lin]

    out_vals = apply_op(f, coo.values(), _op_name="sparse_softmax")
    out = SparseCooTensor(coo._indices, out_vals, coo._shape,
                          coalesced=True)
    return out.to_sparse_csr() if csr is not None else out


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return softmax(x, self._axis)


class BatchNorm(Layer):
    """BatchNorm over the channel (last) dim of COO values — matches
    paddle.sparse.nn.BatchNorm (NDHWC layout, norm over channels)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 name=None):
        super().__init__()
        from ..nn.layer.norm import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon)

    def forward(self, x: SparseCooTensor):
        vals = x.values()
        out_vals = self._bn(vals)
        return SparseCooTensor(x._indices, out_vals, x._shape,
                               x._coalesced)


class SyncBatchNorm(BatchNorm):
    """On TPU, batch stats under pjit are already global (XLA reduces over
    the sharded batch axis) so Sync==local BatchNorm by construction."""


def _subm_conv_rulebook(x: SparseCooTensor, weight, bias, padding,
                        dilation, dims):
    """Real sparse submanifold conv: host-built rulebook + device
    gather/GEMM/scatter (reference conv_kernel.cu). Compute scales with
    nnz, not voxel volume — see sparse/rulebook.py. Caller
    (_dense_conv_nd) guarantees per-dim int padding/dilation."""
    import numpy as np
    from ..nn.layer.conv import _ntuple
    from .rulebook import apply_rulebook, build_subm_rulebook

    coo = x.coalesce() if not x._coalesced else x
    spatial = tuple(coo._shape[1:1 + dims])
    ks = tuple(weight.shape[2:2 + dims])
    dil = _ntuple(dilation, dims)
    pad = _ntuple(padding, dims)
    idx_np = np.asarray(coo._indices)
    in_idx, out_idx, _ = build_subm_rulebook(idx_np, spatial, ks, dil,
                                             pad)
    nnz = idx_np.shape[1]

    def f(vals, w, *maybe_bias):
        import jax.numpy as jnp
        K = in_idx.shape[0]
        # [Cout, Cin, *ks] -> [K, Cin, Cout]
        wk = jnp.moveaxis(w.reshape(w.shape[0], w.shape[1], K),
                          (0, 1, 2), (2, 1, 0))
        out = apply_rulebook(vals, wk, in_idx, out_idx, nnz)
        if maybe_bias:
            out = out + maybe_bias[0]
        return out.astype(vals.dtype)

    args = (coo.values(), weight) + (() if bias is None else (bias,))
    out_vals = apply_op(f, *args, _op_name="subm_conv_rulebook")
    out_shape = tuple(coo._shape[:-1]) + (weight.shape[0],)
    return SparseCooTensor(coo._indices, out_vals, out_shape,
                           coalesced=True)


def _dense_conv_nd(x: SparseCooTensor, weight, bias, stride, padding,
                   dilation, groups, dims, subm):
    if subm and groups == 1:
        from ..nn.layer.conv import _ntuple
        strides = _ntuple(stride, dims)
        pad_t = _ntuple(padding, dims)
        dil_t = _ntuple(dilation, dims)

        def _ints(t):
            return len(t) == dims and all(
                isinstance(v, (int,)) and not isinstance(v, bool)
                for v in t)

        if all(s == 1 for s in strides) and _ints(pad_t) \
                and _ints(dil_t):
            # stride-1 submanifold with plain per-dim int geometry: the
            # rulebook path (output support == input support; padding
            # only shifts the window). String/asymmetric paddings keep
            # the dense lowering below, which resolves them.
            return _subm_conv_rulebook(x, weight, bias, pad_t, dil_t,
                                       dims)
    dense = x.to_dense()
    conv = F_dense.conv3d if dims == 3 else F_dense.conv2d
    fmt = "NDHWC" if dims == 3 else "NHWC"
    # conv WITHOUT bias so the output support stays the kernel-reachable
    # set; bias is added to stored values only (implicit zeros stay zero,
    # matching reference sparse-conv semantics).
    out = conv(dense, weight, bias=None, stride=stride, padding=padding,
               dilation=dilation, groups=groups, data_format=fmt)
    if subm:
        # submanifold: output support == input support — only valid when
        # the conv preserves spatial dims (stride 1, 'same' padding)
        if list(out.shape) != list(x._shape[:-1]) + [out.shape[-1]]:
            raise ValueError(
                f"SubmConv requires output spatial dims == input "
                f"({x._shape[:-1]}), got {out.shape[:-1]}; use stride=1 "
                f"and padding=kernel_size//2")
        mask_idx = tuple(x._indices)
        vals = apply_op(lambda o: o[mask_idx], out, _op_name="subm_mask")
        if bias is not None:
            vals = apply_op(lambda v, b: v + b, vals, bias,
                            _op_name="subm_bias")
        return SparseCooTensor(x._indices, vals,
                               tuple(out.shape), coalesced=True)
    res = to_sparse_coo(out, len(out.shape) - 1)
    if bias is not None:
        vals = apply_op(lambda v, b: v + b, res.values(), bias,
                        _op_name="sparse_conv_bias")
        res = SparseCooTensor(res._indices, vals, res._shape,
                              coalesced=True)
    return res


class _SparseConvNd(Layer):
    _dims = 3
    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None,
                 key=None):
        super().__init__()
        import numpy as np
        from ..nn import initializer as I
        d = self._dims
        ks = tuple(kernel_size) if isinstance(kernel_size, (list, tuple)) \
            else (kernel_size,) * d
        w_shape = [out_channels, in_channels // groups, *ks]
        fan_in = (in_channels // groups) * int(np.prod(ks))
        self.weight = self.create_parameter(
            shape=w_shape, attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        if bias_attr is not False:
            bound = 1.0 / np.sqrt(fan_in)
            self.bias = self.create_parameter(
                shape=[out_channels], attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))
        else:
            self.bias = None
        self._cfg = (stride, padding, dilation, groups)

    def forward(self, x):
        stride, padding, dilation, groups = self._cfg
        return _dense_conv_nd(x, self.weight, self.bias, stride, padding,
                              dilation, groups, self._dims, self._subm)


class Conv3D(_SparseConvNd):
    _dims, _subm = 3, False


class SubmConv3D(_SparseConvNd):
    _dims, _subm = 3, True


class Conv2D(_SparseConvNd):
    _dims, _subm = 2, False


class SubmConv2D(_SparseConvNd):
    _dims, _subm = 2, True


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, return_mask=False, data_format="NDHWC",
                 name=None):
        super().__init__()
        self._k, self._s, self._p = kernel_size, stride, padding

    def forward(self, x: SparseCooTensor):
        dense = x.to_dense()
        # pool kernels are NCDHW; sparse layout is NDHWC — transpose around
        ncdhw = apply_op(lambda a: jnp.transpose(a, (0, 4, 1, 2, 3)),
                         dense, _op_name="to_ncdhw")
        out = F_dense.max_pool3d(ncdhw, self._k, stride=self._s,
                                 padding=self._p)
        out = apply_op(lambda a: jnp.transpose(a, (0, 2, 3, 4, 1)), out,
                       _op_name="to_ndhwc")
        return to_sparse_coo(out, len(out.shape) - 1)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse (SDDMM + SpMM) attention: scores only at mask's pattern.
    Reference: paddle/phi/kernels/sparse/gpu/fused_attention_kernel.cu."""
    if key_padding_mask is not None or attn_mask is not None:
        raise NotImplementedError(
            "sparse attention: encode padding/attn masks into sparse_mask's "
            "pattern instead")
    import math
    d = query.shape[-1]
    scale = 1.0 / math.sqrt(d)
    b, h = query.shape[0], query.shape[1]
    outs = []
    for bi in range(b):
        for hi in range(h):
            q = apply_op(lambda a: a[bi, hi] * scale, query,
                         _op_name="slice_q")
            k = apply_op(lambda a: a[bi, hi].T, key, _op_name="slice_k")
            v = apply_op(lambda a: a[bi, hi], value, _op_name="slice_v")
            scores = sp_ops.masked_matmul(q, k, sparse_mask)
            probs = softmax(scores, axis=-1)
            outs.append(sp_ops.matmul(probs, v))
    import jax.numpy as _j

    def stack(*arrs):
        return _j.stack(arrs).reshape((b, h) + arrs[0].shape)

    return apply_op(stack, *outs, _op_name="sparse_attn_stack")


functional = type("functional", (), {
    "relu": staticmethod(sp_ops.relu),
    "relu6": staticmethod(sp_ops.relu6),
    "leaky_relu": staticmethod(sp_ops.leaky_relu),
    "softmax": staticmethod(softmax),
    "attention": staticmethod(attention),
})()
