"""Rulebook sparse convolution: gather → per-offset GEMM → scatter-add.

Reference: paddle/phi/kernels/sparse/gpu/conv_kernel.cu + conv.cu.h —
the GPU path builds a "rulebook" of (kernel_offset, in_idx, out_idx)
triples, then runs one gathered GEMM per kernel offset. Same
decomposition here, split TPU-first:

- Rulebook CONSTRUCTION is host-side numpy over the COO indices
  (eager indices are concrete; XLA wants static shapes, and the
  pair-counts are data-dependent). Buckets are padded to power-of-two
  capacities so the device program recompiles O(log nnz) times, not
  per batch.
- Rulebook APPLICATION is one jitted program: for each kernel offset
  k, ``out[out_k] += vals[in_k] @ W[k]`` — a dense [n_k, Cin]x[Cin,
  Cout] MXU matmul per offset (K=27 for 3³ kernels), with sentinel
  indices pointing at a zero pad row so padding contributes nothing.

Compute scales with nnz (sum of bucket sizes ~ nnz * avg kernel
occupancy), NOT with the dense voxel volume — the property the
reference's sparse conv exists for (SubmConv on LiDAR voxel grids at
<<1% density).
"""
from __future__ import annotations

import hashlib
from itertools import product
from typing import Tuple

import numpy as np

__all__ = ["build_subm_rulebook", "apply_rulebook"]

_RULEBOOK_CACHE: dict = {}
_CACHE_LIMIT = 64


def _pad_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return max(p, 8)


def build_subm_rulebook(indices: np.ndarray, spatial: Tuple[int, ...],
                        kernel_size: Tuple[int, ...],
                        dilation: Tuple[int, ...],
                        padding: Tuple[int, ...]):
    """Submanifold rulebook: output support == input support.

    indices: [1 + d, nnz] int array (batch + d spatial coords, NDHWC
    order without the channel dim). Returns (in_idx, out_idx) arrays of
    shape [K, cap] padded with ``nnz`` (the zero-row sentinel), plus
    the per-offset pair counts. The neighbor relation follows the
    reference conv geometry at stride 1: ``q = p - padding +
    off*dilation`` — padding = (kernel_size//2)*dilation centers the
    window; other paddings shift it (same semantics as the reference
    rulebook, which never raises for off-center subm windows).
    """
    key = (hashlib.sha1(np.ascontiguousarray(indices)).hexdigest(),
           tuple(spatial), tuple(kernel_size), tuple(dilation),
           tuple(padding))
    hit = _RULEBOOK_CACHE.get(key)
    if hit is not None:
        return hit
    nd = len(spatial)
    nnz = indices.shape[1]
    coords = indices.T.astype(np.int64)          # [nnz, 1+d]
    # linearize (batch, spatial...) for O(log n) membership via sort
    mults = np.ones(nd + 1, np.int64)
    for i in range(nd - 1, -1, -1):
        mults[i] = mults[i + 1] * spatial[i]
    lin = coords @ mults
    order = np.argsort(lin)
    lin_sorted = lin[order]

    in_list, out_list, counts = [], [], []
    for off in product(*[range(k) for k in kernel_size]):
        delta = np.array([0] + [o * dil - p for o, dil, p
                                in zip(off, dilation, padding)],
                         np.int64)
        q = coords + delta
        ok = np.ones(nnz, bool)
        for i in range(nd):
            ok &= (q[:, 1 + i] >= 0) & (q[:, 1 + i] < spatial[i])
        qlin = q[ok] @ mults
        pos = np.searchsorted(lin_sorted, qlin)
        pos = np.clip(pos, 0, nnz - 1)
        found = lin_sorted[pos] == qlin
        out_rows = np.nonzero(ok)[0][found]      # output = point p
        in_rows = order[pos[found]]              # input  = neighbor q
        in_list.append(in_rows)
        out_list.append(out_rows)
        counts.append(len(in_rows))

    cap = _pad_pow2(max(counts) if counts else 1)
    K = len(in_list)
    in_idx = np.full((K, cap), nnz, np.int32)    # nnz = zero-row pad
    out_idx = np.full((K, cap), nnz, np.int32)
    for k in range(K):
        in_idx[k, :counts[k]] = in_list[k]
        out_idx[k, :counts[k]] = out_list[k]
    if len(_RULEBOOK_CACHE) >= _CACHE_LIMIT:
        _RULEBOOK_CACHE.pop(next(iter(_RULEBOOK_CACHE)))
    res = (in_idx, out_idx, np.asarray(counts, np.int64))
    _RULEBOOK_CACHE[key] = res
    return res


def apply_rulebook(values, weight_k, in_idx, out_idx, nnz: int):
    """out[out_idx[k]] += values[in_idx[k]] @ weight_k[k] for all k, in
    one traceable program.

    values: [nnz, Cin]; weight_k: [K, Cin, Cout]; in_idx/out_idx:
    [K, cap] with sentinel ``nnz`` rows contributing zero.
    """
    import jax.numpy as jnp

    K = in_idx.shape[0]
    cout = weight_k.shape[-1]
    vpad = jnp.concatenate(
        [values, jnp.zeros((1, values.shape[-1]), values.dtype)], 0)
    out = jnp.zeros((nnz + 1, cout),
                    jnp.promote_types(values.dtype, weight_k.dtype))
    for k in range(K):           # K is small & static (27 for 3x3x3)
        gathered = vpad[in_idx[k]]              # [cap, Cin]
        contrib = gathered @ weight_k[k]        # MXU GEMM
        out = out.at[out_idx[k]].add(contrib)
    return out[:nnz]
