"""Sparse tensor types, TPU-native.

Reference capability: ``phi::SparseCooTensor`` / ``phi::SparseCsrTensor``
(/root/reference/paddle/phi/core/sparse_coo_tensor.h,
/root/reference/paddle/phi/core/sparse_csr_tensor.h) and the Python surface
``paddle.sparse`` (/root/reference/python/paddle/sparse/__init__.py).

TPU-first design: XLA has no native sparse formats, so sparse tensors here
are *structs of dense arrays* — COO = (indices [ndim, nnz], values [nnz, ...]),
CSR = (crows, cols, values) — and every op lowers to gather / scatter-add /
segment reductions, which XLA tiles well. ``values`` is a framework
``Tensor`` so autograd flows through sparse ops via the same vjp tape as
dense ops (no separate sparse grad kernels, unlike the reference's
``phi/kernels/sparse``).
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, apply_op


def _as_tensor(x, dtype=None) -> Tensor:
    if isinstance(x, Tensor):
        return x.astype(dtype) if dtype is not None else x
    return Tensor(jnp.asarray(x, dtype=dtype) if dtype is not None
                  else jnp.asarray(x))


def _as_index_array(x) -> jnp.ndarray:
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return arr.astype(jnp.int32)


class SparseCooTensor:
    """COO sparse tensor: ``indices`` [sparse_ndim, nnz] + ``values``
    [nnz, *dense_dims] + global ``shape``.

    Mirrors the user contract of paddle's COO tensor
    (``Tensor.is_sparse_coo()``, ``.indices()``, ``.values()``,
    ``.to_dense()``); gradient support flows through ``values``.
    """

    is_sparse = True
    format = "coo"

    def __init__(self, indices, values, shape, coalesced: bool = False):
        self._indices = _as_index_array(indices)
        self._values = _as_tensor(values)
        self._shape = tuple(int(s) for s in shape)
        self._coalesced = coalesced
        if self._indices.ndim != 2:
            raise ValueError("indices must be [sparse_ndim, nnz]")
        sparse_ndim = self._indices.shape[0]
        dense_ndim = len(self._values.shape) - 1
        if sparse_ndim + dense_ndim != len(self._shape):
            raise ValueError(
                f"sparse_ndim({sparse_ndim}) + dense_ndim({dense_ndim}) "
                f"!= ndim({len(self._shape)})")

    # -- metadata ---------------------------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def stop_gradient(self):
        return self._values.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._values.stop_gradient = v

    @property
    def sparse_ndim(self) -> int:
        return int(self._indices.shape[0])

    def nnz(self) -> int:
        return int(self._indices.shape[1])

    def indices(self) -> Tensor:
        return Tensor(self._indices)

    def values(self) -> Tensor:
        return self._values

    def is_sparse_coo(self) -> bool:
        return True

    def is_sparse_csr(self) -> bool:
        return False

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    # -- conversions ------------------------------------------------------
    def to_dense(self) -> Tensor:
        idx = self._indices
        shape = self._shape

        def scatter(vals):
            out = jnp.zeros(shape, dtype=vals.dtype)
            return out.at[tuple(idx)].add(vals)

        return apply_op(scatter, self._values, _op_name="sparse_to_dense")

    def to_sparse_coo(self, sparse_dim=None) -> "SparseCooTensor":
        return self

    def to_sparse_csr(self) -> "SparseCsrTensor":
        from .creation import _coo_to_csr
        return _coo_to_csr(self.coalesce())

    def coalesce(self) -> "SparseCooTensor":
        """Sum duplicate coordinates (reference:
        paddle/phi/kernels/sparse/coalesce_kernel.h). Segment-sum over a
        linearized key — a TPU-friendly sorted reduction."""
        if self._coalesced or self.nnz() == 0:
            return self
        idx = self._indices
        # column-wise unique (lexicographic) — no index linearization, so
        # no int32 overflow for large sparse shapes
        uniq, inv = jnp.unique(idx, axis=1, return_inverse=True,
                               size=idx.shape[1], fill_value=-1)
        n_out = int((uniq[0] >= 0).sum())
        new_idx = uniq[:, :n_out]

        def seg(vals):
            import jax
            return jax.ops.segment_sum(vals, inv.reshape(-1),
                                       num_segments=n_out)

        new_vals = apply_op(seg, self._values, _op_name="sparse_coalesce")
        return SparseCooTensor(new_idx, new_vals, self._shape,
                               coalesced=True)

    def transpose(self, perm) -> "SparseCooTensor":
        perm = list(perm)
        if sorted(perm) != list(range(self.sparse_ndim)):
            raise NotImplementedError(
                "sparse transpose supports sparse dims only")
        new_idx = self._indices[jnp.asarray(perm)]
        new_shape = tuple(self._shape[p] for p in perm) \
            + self._shape[self.sparse_ndim:]
        return SparseCooTensor(new_idx, self._values, new_shape)

    def numpy(self) -> np.ndarray:
        return self.to_dense().numpy()

    def astype(self, dt) -> "SparseCooTensor":
        return SparseCooTensor(self._indices, self._values.astype(dt),
                               self._shape, self._coalesced)

    def detach(self) -> "SparseCooTensor":
        return SparseCooTensor(self._indices, self._values.detach(),
                               self._shape, self._coalesced)


class SparseCsrTensor:
    """CSR sparse matrix (optionally batched): ``crows`` [(B,) nrows+1],
    ``cols`` [(B,) nnz], ``values``.

    Reference: /root/reference/paddle/phi/core/sparse_csr_tensor.h.
    """

    is_sparse = True
    format = "csr"

    def __init__(self, crows, cols, values, shape):
        self._crows = _as_index_array(crows)
        self._cols = _as_index_array(cols)
        self._values = _as_tensor(values)
        self._shape = tuple(int(s) for s in shape)
        if len(self._shape) not in (2, 3):
            raise ValueError("CSR supports 2-D or batched 3-D")

    @property
    def shape(self) -> List[int]:
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    def nnz(self) -> int:
        return int(self._cols.shape[-1])

    def crows(self) -> Tensor:
        return Tensor(self._crows)

    def cols(self) -> Tensor:
        return Tensor(self._cols)

    def values(self) -> Tensor:
        return self._values

    def is_sparse_coo(self) -> bool:
        return False

    def is_sparse_csr(self) -> bool:
        return True

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    def _rows(self) -> jnp.ndarray:
        """Expand crows to a per-nnz row index (CSR→COO row vector)."""
        nrows = self._shape[-2]
        nnz = self._cols.shape[-1]
        pos = jnp.arange(nnz, dtype=jnp.int32)

        def expand(crows1d):
            return jnp.searchsorted(crows1d[1:], pos, side="right") \
                .astype(jnp.int32)

        if self._crows.ndim == 1:
            return expand(self._crows)
        import jax
        return jax.vmap(expand)(self._crows)

    def to_sparse_coo(self, sparse_dim: Optional[int] = None) \
            -> SparseCooTensor:
        rows = self._rows()
        if len(self._shape) == 2:
            idx = jnp.stack([rows, self._cols])
        else:
            b = self._crows.shape[0]
            nnz = self._cols.shape[-1]
            batch = jnp.repeat(jnp.arange(b, dtype=jnp.int32), nnz)
            idx = jnp.stack([batch, rows.reshape(-1),
                             self._cols.reshape(-1)])
        vals = self._values
        if len(self._shape) == 3 and len(vals.shape) > 1:
            vals = apply_op(lambda v: v.reshape(-1), vals,
                            _op_name="csr_flatten_values")
        return SparseCooTensor(idx, vals, self._shape, coalesced=True)

    def to_dense(self) -> Tensor:
        return self.to_sparse_coo().to_dense()

    def numpy(self) -> np.ndarray:
        return self.to_dense().numpy()

    def astype(self, dt) -> "SparseCsrTensor":
        return SparseCsrTensor(self._crows, self._cols,
                               self._values.astype(dt), self._shape)

    def detach(self) -> "SparseCsrTensor":
        return SparseCsrTensor(self._crows, self._cols,
                               self._values.detach(), self._shape)


def is_sparse(x) -> bool:
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))
