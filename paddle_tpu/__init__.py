"""paddle_tpu: a TPU-native deep learning framework with PaddlePaddle's
capabilities, built on JAX/XLA/Pallas.

Public surface mirrors ``import paddle`` (reference:
/root/reference/python/paddle/__init__.py): eager Tensors with autograd,
nn.Layer modules, optimizers, AMP, DataLoader, distributed parallelism, jit
capture — re-architected TPU-first (see SURVEY.md §7).
"""
from .framework.dtype import (  # noqa: F401
    bool_ as bool, uint8, int8, int16, int32, int64, float16, bfloat16,
    float32, float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
    DType as dtype, get_default_dtype, set_default_dtype)
from .framework import (  # noqa: F401
    Tensor, no_grad, enable_grad, set_grad_enabled, seed,
    get_rng_state, set_rng_state, in_dynamic_mode, in_pir_mode)
from .framework.flags import get_flags, set_flags  # noqa: F401
from .framework.tensor import Parameter  # noqa: F401
from .ops import *  # noqa: F401,F403
from .ops import creation as _creation  # noqa: F401
from .device import (  # noqa: F401
    set_device, get_device, is_compiled_with_cuda, CPUPlace, CUDAPlace,
    CUDAPinnedPlace, TPUPlace)
from . import device  # noqa: F401
from . import autograd  # noqa: F401
from .autograd import grad  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import vision  # noqa: F401
from . import metric  # noqa: F401
from . import profiler  # noqa: F401
from . import distribution  # noqa: F401
from . import incubate  # noqa: F401
from . import distributed  # noqa: F401
from . import static  # noqa: F401
from . import cost_model  # noqa: F401
from . import decomposition  # noqa: F401
from . import sparse  # noqa: F401
from . import quantization  # noqa: F401
from . import audio  # noqa: F401
from . import geometric  # noqa: F401
from . import text  # noqa: F401
from . import inference  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import linalg  # noqa: F401
from . import utils  # noqa: F401
from . import hub  # noqa: F401
from . import regularizer  # noqa: F401
from . import onnx  # noqa: F401
from . import sysconfig  # noqa: F401
from . import callbacks  # noqa: F401
from .regularizer import L1Decay, L2Decay  # noqa: F401
from .hapi.model import Model  # noqa: F401
from .framework.io import save, load  # noqa: F401
from .tensor_module import tensor  # noqa: F401
from .nn.layer_base import ParamAttr  # noqa: F401
from .distributed.parallel import DataParallel  # noqa: F401
from .static.graph import create_parameter  # noqa: F401

def disable_static(place=None):
    from .static.graph import disable_static_mode
    disable_static_mode()
    return None


def enable_static():
    from .static.graph import enable_static_mode
    enable_static_mode()


def in_dygraph_mode():
    return in_dynamic_mode()


def is_grad_enabled():
    from .framework.tensor import grad_enabled
    return grad_enabled()


def disable_signal_handler():
    return None


__version__ = "0.1.0"
