"""paddle_tpu: a TPU-native deep learning framework with PaddlePaddle's
capabilities, built on JAX/XLA/Pallas.

Public surface mirrors ``import paddle`` (reference:
/root/reference/python/paddle/__init__.py): eager Tensors with autograd,
nn.Layer modules, optimizers, AMP, DataLoader, distributed parallelism, jit
capture — re-architected TPU-first (see SURVEY.md §7).
"""
import jax as _jax

# -- jax version shims: the codebase targets the current jax surface;
# alias the few renamed/moved APIs so older lines (e.g. 0.4.x in this
# image) serve the same programs. --------------------------------------
# True when running on a pre-jax.shard_map jax: the experimental
# shard_map backing the alias below cannot lower axis_index/ppermute
# inside PARTIAL-AUTO regions (pipe-parallel paths); tests gate on it.
_jax_compat_old_shard_map = not hasattr(_jax, "shard_map")

if _jax_compat_old_shard_map:
    # jax < 0.5 ships shard_map under experimental only, with the old
    # kwarg surface (check_rep/auto instead of check_vma/axis_names)
    # and a REQUIRED mesh; adapt it so the `jax.shard_map(...)` call
    # sites (and `from jax import shard_map` imports below) work on
    # both lines. Call sites that omit mesh= rely on the jax.set_mesh
    # context — the set_mesh shim below records it here.
    from jax.experimental.shard_map import shard_map as _shard_map_old

    _compat_mesh = [None]

    def _shard_map(f, *, mesh=None, in_specs, out_specs,
                   check_vma=None, check_rep=None, axis_names=None,
                   auto=None):
        if mesh is None:
            mesh = _compat_mesh[0]
        if mesh is None:
            raise RuntimeError(
                "jax.shard_map without mesh= needs an enclosing "
                "jax.set_mesh(...) on this pre-0.5 jax")
        if auto is None and axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        kw = {}
        if auto:
            kw["auto"] = auto
        rep = check_rep if check_rep is not None else check_vma
        if rep is not None:
            kw["check_rep"] = rep
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)

    _jax.shard_map = _shard_map
import jax.export  # noqa: F401  (0.4.x: not loaded by `import jax`)
if not hasattr(_jax, "set_mesh"):
    # pre-set_mesh jax: sharding is carried entirely by the
    # NamedShardings already attached to every jitted step, so the
    # context degrades to recording the mesh for the shard_map shim
    # and otherwise doing nothing. (Entering the legacy `with mesh:`
    # resource env instead would flip pjit into the xmap-era axis-env
    # lowering, which emits PartitionId ops XLA's SPMD partitioner
    # rejects.)
    import contextlib as _contextlib

    @_contextlib.contextmanager
    def _set_mesh(mesh):
        if not _jax_compat_old_shard_map:
            yield mesh
            return
        prev, _compat_mesh[0] = _compat_mesh[0], mesh
        try:
            yield mesh
        finally:
            _compat_mesh[0] = prev

    _jax.set_mesh = _set_mesh
try:
    import jax.experimental.pallas.tpu as _pltpu
    if not hasattr(_pltpu, "CompilerParams"):
        _pltpu.CompilerParams = _pltpu.TPUCompilerParams
except ImportError:  # pallas absent: kernels gate on backend anyway
    pass

from .framework.dtype import (  # noqa: F401
    bool_ as bool, uint8, int8, int16, int32, int64, float16, bfloat16,
    float32, float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
    DType as dtype, get_default_dtype, set_default_dtype)
from .framework import (  # noqa: F401
    Tensor, no_grad, enable_grad, set_grad_enabled, seed,
    get_rng_state, set_rng_state, in_dynamic_mode, in_pir_mode)
from .framework.flags import get_flags, set_flags  # noqa: F401
from .framework.tensor import Parameter  # noqa: F401
from .ops import *  # noqa: F401,F403
from .ops import creation as _creation  # noqa: F401
from .device import (  # noqa: F401
    set_device, get_device, is_compiled_with_cuda, CPUPlace, CUDAPlace,
    CUDAPinnedPlace, TPUPlace)
from . import device  # noqa: F401
from . import autograd  # noqa: F401
from .autograd import grad  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import vision  # noqa: F401
from . import metric  # noqa: F401
from . import profiler  # noqa: F401
from . import observability  # noqa: F401
from . import resilience  # noqa: F401
from . import distribution  # noqa: F401
from . import incubate  # noqa: F401
from . import distributed  # noqa: F401
from . import static  # noqa: F401
from . import cost_model  # noqa: F401
from . import decomposition  # noqa: F401
from . import sparse  # noqa: F401
from . import quantization  # noqa: F401
from . import audio  # noqa: F401
from . import geometric  # noqa: F401
from . import text  # noqa: F401
from . import inference  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import linalg  # noqa: F401
from . import utils  # noqa: F401
from . import hub  # noqa: F401
from . import regularizer  # noqa: F401
from . import onnx  # noqa: F401
from . import sysconfig  # noqa: F401
from . import callbacks  # noqa: F401
from .regularizer import L1Decay, L2Decay  # noqa: F401
from .hapi.model import Model  # noqa: F401
from .framework.io import save, load  # noqa: F401
from .tensor_module import tensor  # noqa: F401
from .nn.layer_base import ParamAttr  # noqa: F401
from .distributed.parallel import DataParallel  # noqa: F401
from .static.graph import create_parameter  # noqa: F401

def disable_static(place=None):
    from .static.graph import disable_static_mode
    disable_static_mode()
    return None


def enable_static():
    from .static.graph import enable_static_mode
    enable_static_mode()


def in_dygraph_mode():
    return in_dynamic_mode()


def is_grad_enabled():
    from .framework.tensor import grad_enabled
    return grad_enabled()


def disable_signal_handler():
    return None


__version__ = "0.1.0"
