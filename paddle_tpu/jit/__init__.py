"""JIT capture + export (reference: python/paddle/jit/, 34.7k LoC)."""
from .static_function import (to_static, not_to_static, StaticFunction,
                              InputSpec)
from .functional import TrainStep, functional_call, value_and_grad
from .save_load import save, load, TranslatedLayer

__all__ = ["to_static", "not_to_static", "StaticFunction", "InputSpec",
           "TrainStep", "functional_call", "value_and_grad", "save", "load",
           "TranslatedLayer"]
