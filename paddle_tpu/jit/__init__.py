"""JIT capture + export (reference: python/paddle/jit/, 34.7k LoC)."""
from .static_function import (to_static, not_to_static, StaticFunction,
                              InputSpec, capture_report,
                              reset_capture_report, capture_telemetry)
from .auto_capture import auto_capture, AutoCapture  # noqa: F401
from .functional import TrainStep, functional_call, value_and_grad
from .save_load import save, load, TranslatedLayer
from . import dy2static  # noqa: F401  (AST control-flow conversion)

__all__ = ["to_static", "not_to_static", "StaticFunction", "InputSpec",
           "TrainStep", "functional_call", "value_and_grad", "save", "load",
           "TranslatedLayer", "capture_report", "reset_capture_report",
           "capture_telemetry", "auto_capture", "AutoCapture"]


# verbosity / capture-control compat (python/paddle/jit/api.py + sot flags)
_to_static_enabled = [True]
_code_level = [0]
_verbosity = [0]


def enable_to_static(enable: bool = True):
    """Globally toggle to_static capture (disabled -> eager passthrough)."""
    _to_static_enabled[0] = bool(enable)


def ignore_module(modules):
    """SOT compat: modules to skip during capture. Trace-based capture has
    no bytecode translation to skip, so this only records the intent."""
    return list(modules) if isinstance(modules, (list, tuple)) else [modules]


def set_code_level(level: int = 100, also_to_stdout: bool = False):
    _code_level[0] = level


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    _verbosity[0] = level
