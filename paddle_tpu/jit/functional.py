"""Functional bridge: Layer/Optimizer → pure jitted step functions.

This is the TPU replacement for the reference's whole-graph executors
(to_static Engine: auto_parallel/static/engine.py; StandaloneExecutor
new_executor/): instead of building a Program and interpreting it, we trace
(forward + backward + optimizer update) into ONE jitted XLA computation.
XLA then owns scheduling, fusion, memory planning, and (under shardings)
collective insertion — the entire executor layer of the reference collapses
into this file plus jax.jit.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, no_grad
from ..nn.layer_base import Layer

__all__ = ["functional_call", "value_and_grad", "TrainStep"]


def functional_call(layer: Layer, params: Dict[str, jax.Array],
                    buffers: Optional[Dict[str, jax.Array]], *args,
                    **kwargs):
    """Run layer.forward as a pure function of (params, buffers, inputs).

    Returns (outputs_arrays, new_buffers) — buffer mutations (e.g. BN
    running stats) are captured functionally.
    """
    wrapped = [Tensor(a, stop_gradient=True) if isinstance(
        a, (jax.Array, jax.core.Tracer)) else a for a in args]
    with layer.bind_state(params, buffers):
        out = layer(*wrapped, **kwargs)
        new_buffers = {n: b._data for n, b in layer.named_buffers()
                       if b is not None}
    if isinstance(out, (tuple, list)):
        out_arr = tuple(o._data if isinstance(o, Tensor) else o for o in out)
    else:
        out_arr = out._data if isinstance(out, Tensor) else out
    return out_arr, new_buffers


def value_and_grad(layer: Layer, loss_fn: Callable,
                   return_outputs: bool = False):
    """Build fn(params, buffers, *batch) -> ((loss, aux), grads) where
    aux is new_buffers, or (new_buffers, outputs) with return_outputs.

    loss_fn receives (output_tensor(s), *batch_labels_tensors) and must
    return a scalar Tensor (or a list whose entries are summed).
    Differentiates w.r.t. params only.
    """
    def compute(params, buffers, inputs, labels):
        out_arr, new_buffers = functional_call(layer, params, buffers,
                                               *inputs)
        outs = out_arr if isinstance(out_arr, tuple) else (out_arr,)
        out_tensors = [Tensor(o, stop_gradient=True) for o in outs]
        label_tensors = [Tensor(l, stop_gradient=True) for l in labels]
        loss = loss_fn(*(out_tensors + label_tensors))
        comps = loss if isinstance(loss, (tuple, list)) else [loss]
        total = comps[0]
        for extra in comps[1:]:
            total = total + extra
        aux = ((new_buffers, outs, tuple(c._data for c in comps))
               if return_outputs else new_buffers)
        return total._data, aux

    return jax.value_and_grad(compute, argnums=0, has_aux=True)


class TrainStep:
    """One fully-jitted training step: forward + grad + optimizer update.

    Usage::

        step = TrainStep(model, opt, lambda out, y: F.cross_entropy(out, y))
        loss = step(x, y)          # params/opt-state updated in place

    The optimizer's update rules run inside the trace (their accumulator
    dict is snapshotted/restored around tracing), so any Optimizer subclass
    works unchanged — the tape never runs; jax.grad supplies gradients.
    """

    def __init__(self, model: Layer, optimizer, loss_fn: Callable,
                 donate: bool = True, return_outputs: bool = False,
                 num_labels: int = 1):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.return_outputs = return_outputs
        self.num_labels = num_labels  # trailing batch entries -> loss_fn
        self._vg = value_and_grad(model, loss_fn, return_outputs)
        self._jitted = None
        self._param_names = [n for n, _ in model.named_parameters()]
        self._donate = donate

    def _build(self):
        opt = self.optimizer
        model = self.model

        def step(params, buffers, opt_state, lr, t, inputs, labels):
            (loss, aux), grads = self._vg(params, buffers, inputs, labels)
            if self.return_outputs:
                new_buffers, outs, comps = aux
            else:
                new_buffers, outs, comps = aux, (), ()
            # run optimizer updates inside the trace
            named = dict(model.named_parameters())
            saved_acc = {k: dict(v) for k, v in opt._accumulators.items()}
            saved_step = opt._step_count
            new_params = {}
            try:
                # route traced accumulator state in
                for n, p in named.items():
                    if p.name in opt_state:
                        opt._accumulators[p.name] = dict(opt_state[p.name])
                opt._step_count = t
                # bypass get_lr()'s float() coercion with the traced lr
                opt.get_lr = lambda: lr
                for n, p in named.items():
                    g = grads.get(n)
                    if g is None or p.stop_gradient:
                        new_params[n] = params[n]
                        continue
                    real_data = p._data
                    p._data = params[n]
                    try:
                        new_params[n] = opt._update_param(p, g).astype(
                            params[n].dtype)
                    finally:
                        p._data = real_data
                new_state = {p.name: dict(opt._accumulators.get(p.name, {}))
                             for p in named.values()}
            finally:
                opt.__dict__.pop("get_lr", None)
                opt._accumulators = saved_acc
                opt._step_count = saved_step
            return loss, new_params, new_buffers, new_state, outs, comps

        donate = (0, 2) if self._donate else ()
        return jax.jit(step, donate_argnums=donate)

    def lower(self, *batch):
        """``jax.jit(...).lower`` for the assembled step — the compiled
        distributed program (StableHLO/optimized HLO via .compile()
        .as_text()) for collective-traffic auditing
        (benchmarks/scaling_model.py)."""
        inputs, labels = self._split(batch)
        if self._jitted is None:
            self._jitted = self._build()
        args = self._assemble(inputs, labels, advance=False)
        return self._jitted.lower(*args)

    def _assemble(self, inputs, labels, advance=True):
        """(params, buffers, opt_state, lr, t, inputs, labels) in the
        jitted step's calling convention, creating optimizer slots on
        first use (shared by __call__ and lower; ``advance=False``
        leaves the optimizer's step counter untouched — lowering is
        not a step)."""
        params, buffers = self.model.raw_state()
        named = dict(self.model.named_parameters())
        opt = self.optimizer
        opt_state = {p.name: dict(opt._accumulators.get(p.name, {}))
                     for p in named.values()}
        # ensure accumulators exist with correct shapes before first trace
        if all(not v for v in opt_state.values()):
            with no_grad():
                # run a sacrificial update so every _acc() slot is CREATED
                # with its optimizer-defined init, while a stubbed
                # _set_acc discards the update's outputs — the warm update
                # runs at _step_count=0 where Adam-family bias correction
                # divides by 1-beta^0 == 0, so its results (NaN master
                # weights under AMP-O2, advanced NAdam/Rprop schedules)
                # must never be stored.
                opt._set_acc = lambda p, name, value: None
                try:
                    # disable_jit: the update rules' inner jits donate
                    # their slot buffers — running them eagerly keeps the
                    # freshly _acc()-created slot arrays alive
                    with jax.disable_jit(), no_grad():
                        for n, p in named.items():
                            if not p.stop_gradient:
                                real = p._data
                                p._data = jnp.copy(real)
                                opt._update_param(p, jnp.zeros_like(real))
                                p._data = real
                finally:
                    del opt.__dict__["_set_acc"]  # back to the class method
            opt_state = {p.name: dict(opt._accumulators.get(p.name, {}))
                         for p in named.values()}
        if advance:
            opt._step_count += 1
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        t = jnp.asarray(opt._step_count + (0 if advance else 1),
                        jnp.int32)
        return (params, buffers, opt_state, lr, t,
                tuple(x._data if isinstance(x, Tensor) else x
                      for x in inputs),
                tuple(y._data if isinstance(y, Tensor) else y
                      for y in labels))

    def __call__(self, *batch) -> Tensor:
        inputs, labels = self._split(batch)
        if self._jitted is None:
            self._jitted = self._build()
        named = dict(self.model.named_parameters())
        opt = self.optimizer
        loss, new_params, new_buffers, new_state, outs, comps = \
            self._jitted(*self._assemble(inputs, labels))
        with no_grad():
            for n, p in named.items():
                p._data = new_params[n]
                p.grad_node = None
            for n, b in self.model.named_buffers():
                if b is not None and n in new_buffers:
                    b._data = new_buffers[n]
            for pname, slots in new_state.items():
                opt._accumulators[pname] = slots
        loss_t = Tensor(loss, stop_gradient=True)
        if self.return_outputs:
            return (loss_t,
                    tuple(Tensor(o, stop_gradient=True) for o in outs),
                    tuple(Tensor(c, stop_gradient=True) for c in comps))
        return loss_t

    def _split(self, batch) -> Tuple[tuple, tuple]:
        n = min(self.num_labels, max(len(batch) - 1, 0))
        if n == 0:
            return tuple(batch), ()
        return tuple(batch[:-n]), tuple(batch[-n:])
