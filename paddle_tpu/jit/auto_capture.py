"""Transparent capture: no-decorator to_static for whole namespaces.

Reference: the SOT eval-frame hook intercepts EVERY frame via PEP 523
(paddle/fluid/pybind/sot/eval_frame.c) so user code gets compiled
without decorating anything. CPython 3.12 removed the sanctioned
Python-level path to frame REPLACEMENT, but ships ``sys.monitoring`` —
observation-only, per-code-object, near-zero overhead when disabled.

TPU-native design: observe PY_START events with sys.monitoring, count
calls per code object, and when a function inside a registered
namespace turns HOT, REBIND it (module attribute / class method) to a
``StaticFunction`` wrapper. Subsequent calls go straight through the
capture tiers (AST -> bytecode -> break-and-resume) with zero
per-call interposition — the rebind IS the interception, monitoring
only decides where it pays. Lambdas/closures that are not reachable as
attributes cannot be rebound and stay eager (reported, not silent).

Usage::

    with paddle.jit.auto_capture(my_models_module, threshold=2):
        train()            # hot functions compile transparently

or ``ac = paddle.jit.auto_capture(mod); ac.start(); ...; ac.stop()``.
"""
from __future__ import annotations

import sys
import types
from typing import Any, Dict, List, Optional

__all__ = ["auto_capture", "AutoCapture"]

_TOOL_NAME = "paddle_tpu.auto_capture"


class AutoCapture:
    def __init__(self, *namespaces, threshold: int = 2):
        if not namespaces:
            raise ValueError("auto_capture needs at least one module "
                             "or class namespace")
        for ns in namespaces:
            if not isinstance(ns, (types.ModuleType, type)):
                raise TypeError(
                    f"namespace must be a module or class, got "
                    f"{type(ns).__name__}")
        self._namespaces = namespaces
        self._threshold = int(threshold)
        self._counts: Dict[Any, int] = {}
        self._rebound: List[tuple] = []   # (owner, name, original)
        self._unreboundable: Dict[str, str] = {}
        self._tool_id: Optional[int] = None
        # code object -> (owner, attr name, function)
        self._index = self._build_index()

    def _build_index(self):
        idx = {}

        def add_owner(owner):
            for name, v in list(vars(owner).items()):
                if isinstance(v, types.FunctionType):
                    if getattr(v, "_not_to_static", False) or \
                            name.startswith("__"):
                        continue
                    idx[v.__code__] = (owner, name, v)
                elif isinstance(v, type) and owner is not v:
                    # classes defined in the module: capture methods
                    mod = getattr(v, "__module__", None)
                    for ns in self._namespaces:
                        if isinstance(ns, types.ModuleType) and \
                                mod == ns.__name__:
                            add_owner(v)
                            break

        for ns in self._namespaces:
            add_owner(ns)
        return idx

    # -- monitoring hook ---------------------------------------------------
    def _on_py_start(self, code, _offset):
        mon = sys.monitoring
        hit = self._index.get(code)
        if hit is None:
            return mon.DISABLE      # never look at this code again
        n = self._counts.get(code, 0) + 1
        self._counts[code] = n
        if n < self._threshold:
            return None
        owner, name, fn = hit
        self._rebind(owner, name, fn)
        del self._index[code]
        return mon.DISABLE

    def _rebind(self, owner, name, fn):
        from ..observability.registry import default_registry
        from .static_function import StaticFunction
        reg = default_registry()
        current = vars(owner).get(name)
        if current is not fn:
            # somebody else rebound it meanwhile — leave theirs alone
            self._unreboundable[f"{owner.__name__}.{name}"] = \
                "attribute changed since indexing"
            reg.counter("ptpu_jit_autocapture_unreboundable_total",
                        "hot functions auto-capture could not rebind"
                        ).inc()
            return
        wrapped = StaticFunction(fn)
        setattr(owner, name, wrapped)
        self._rebound.append((owner, name, fn))
        reg.counter("ptpu_jit_autocapture_rebinds_total",
                    "hot functions transparently rebound to "
                    "StaticFunction").inc()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "AutoCapture":
        if self._tool_id is not None:
            return self
        mon = sys.monitoring
        tid = None
        for cand in range(6):
            if mon.get_tool(cand) is None:
                tid = cand
                break
        if tid is None:
            raise RuntimeError("no free sys.monitoring tool id")
        mon.use_tool_id(tid, _TOOL_NAME)
        mon.register_callback(tid, mon.events.PY_START,
                              self._on_py_start)
        mon.set_events(tid, mon.events.PY_START)
        # per-code DISABLE state survives free_tool_id: without this a
        # session reusing a freed tool id would silently never see
        # PY_START for code objects a PREVIOUS session disabled
        mon.restart_events()
        self._tool_id = tid
        return self

    def stop(self, unbind: bool = False):
        if self._tool_id is not None:
            mon = sys.monitoring
            mon.set_events(self._tool_id, 0)
            mon.register_callback(self._tool_id,
                                  mon.events.PY_START, None)
            mon.free_tool_id(self._tool_id)
            self._tool_id = None
        if unbind:
            for owner, name, fn in reversed(self._rebound):
                setattr(owner, name, fn)
            self._rebound.clear()

    def report(self):
        """What got captured transparently (and what could not be)."""
        return {
            "rebound": [f"{o.__name__}.{n}"
                        for o, n, _ in self._rebound],
            "unreboundable": dict(self._unreboundable),
            "watched": len(self._index),
        }

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def auto_capture(*namespaces, threshold: int = 2) -> AutoCapture:
    """Transparent capture for every function/method defined in the
    given modules or classes: hot functions (>= threshold calls) are
    rebound to ``to_static`` wrappers via a ``sys.monitoring`` observer
    (see module docstring for the PEP-523 relationship)."""
    return AutoCapture(*namespaces, threshold=threshold)
