"""to_static: trace-based graph capture.

Reference: python/paddle/jit/api.py to_static with two capture paths — AST
rewriting (dy2static/program_translator.py:1751) and bytecode JIT (sot/,
~23k LoC + PEP-523 C hook). TPU-native: the Tensor façade dispatches every
op through jax functions, so ordinary jax.jit tracing captures the whole
model without AST or bytecode machinery (SURVEY.md §7 hard part #4 —
trace-based capture with shape/dtype guards via jax.jit's cache; python
control flow on tensor *values* falls back to eager like SOT graph breaks).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np

from ..framework.tensor import Tensor, no_grad
from ..nn.layer_base import Layer
from .functional import functional_call

__all__ = ["to_static", "not_to_static", "StaticFunction", "InputSpec"]


class InputSpec:
    """paddle.static.InputSpec analog (shape with None = dynamic dim)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


class StaticFunction:
    def __init__(self, function: Callable, input_spec=None,
                 build_strategy=None, full_graph=True, backend=None):
        if isinstance(function, Layer):
            self._layer = function
            self._fn = type(function).forward
            self._bound_self = function
        elif hasattr(function, "__self__") and isinstance(
                function.__self__, Layer):
            self._layer = function.__self__
            self._fn = function.__func__
            self._bound_self = function.__self__
        else:
            self._layer = None
            self._fn = function
            self._bound_self = None
        self._input_spec = input_spec
        self._jitted = None
        functools.update_wrapper(self, self._fn)

    @property
    def layer(self):
        return self._layer

    def _build(self):
        layer = self._layer
        # AST pass first (dy2static.py): tensor-dependent if/while/for
        # become lax.cond/while_loop instead of tracer errors; returns
        # the original fn unchanged when conversion isn't possible
        if not getattr(self._fn, "_not_to_static", False):
            from .dy2static import convert_to_static
            fn = convert_to_static(self._fn)
        else:
            fn = self._fn

        if layer is not None:
            def pure(params, buffers, training, *arg_arrays):
                layer.train() if training else layer.eval()
                wrapped = [Tensor(a) if isinstance(
                    a, (jax.Array, jax.core.Tracer)) else a
                    for a in arg_arrays]
                with layer.bind_state(params, buffers):
                    out = fn(layer, *wrapped)
                    new_buffers = {n: b._data
                                   for n, b in layer.named_buffers()
                                   if b is not None}
                return _unwrap_tree(out), new_buffers
            return jax.jit(pure, static_argnums=(2,))

        def pure(*arg_arrays):
            wrapped = [Tensor(a) if isinstance(
                a, (jax.Array, jax.core.Tracer)) else a
                for a in arg_arrays]
            return _unwrap_tree(fn(*wrapped))
        return jax.jit(pure)

    def __call__(self, *args, **kwargs):
        from . import _to_static_enabled
        if not _to_static_enabled[0]:
            # paddle.jit.enable_to_static(False): eager passthrough
            if self._bound_self is not None:
                return self._fn(self._bound_self, *args, **kwargs)
            return self._fn(*args, **kwargs)
        if kwargs:
            # keyword args force eager fallback (graph-break semantics)
            if self._bound_self is not None:
                return self._fn(self._bound_self, *args, **kwargs)
            return self._fn(*args, **kwargs)
        if self._jitted is None:
            self._jitted = self._build()
        arg_arrays = tuple(a._data if isinstance(a, Tensor) else a
                           for a in args)
        if self._layer is not None:
            params, buffers = self._layer.raw_state()
            out, new_buffers = self._jitted(params, buffers,
                                            self._layer.training,
                                            *arg_arrays)
            with no_grad():
                for n, b in self._layer.named_buffers():
                    if b is not None and n in new_buffers:
                        b._data = new_buffers[n]
            return _wrap_tree(out)
        return _wrap_tree(self._jitted(*arg_arrays))

    def concrete_program_specify_input_spec(self, *a, **k):
        return None

    @property
    def code(self):
        import inspect
        return inspect.getsource(self._fn)


def _unwrap_tree(out):
    if isinstance(out, Tensor):
        return out._data
    if isinstance(out, (tuple, list)):
        return tuple(_unwrap_tree(o) for o in out)
    if isinstance(out, dict):
        return {k: _unwrap_tree(v) for k, v in out.items()}
    return out


def _wrap_tree(out):
    if isinstance(out, (jax.Array, np.ndarray)):
        return Tensor(out)
    if isinstance(out, (tuple, list)):
        return tuple(_wrap_tree(o) for o in out)
    if isinstance(out, dict):
        return {k: _wrap_tree(v) for k, v in out.items()}
    return out


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """paddle.jit.to_static analog (decorator or call form)."""
    def decorate(fn):
        return StaticFunction(fn, input_spec, build_strategy,
                              backend=backend)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(function):
    function._not_to_static = True
    return function
