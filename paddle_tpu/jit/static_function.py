"""to_static: trace-based graph capture with SOT-style guards.

Reference: python/paddle/jit/api.py to_static with two capture paths — AST
rewriting (dy2static/program_translator.py:1751) and bytecode JIT (sot/,
~23k LoC + PEP-523 C hook). TPU-native: the Tensor façade dispatches every
op through jax functions, so ordinary jax.jit tracing captures the whole
model without AST or bytecode machinery (SURVEY.md §7 hard part #4).

Guard semantics (the down-payment on SOT's guard system,
sot/opcode_translator/executor/guard.py): tensor args are guarded on
shape+dtype by jax.jit's own cache; NON-tensor args (python scalars,
strings, tuples/lists of scalars, None) become STATIC guards — each
distinct value keys a separate compiled program, so `if flag:` python
branching on a bool argument specializes per value instead of raising a
tracer error or falling back to eager. Keyword args participate the
same way (bound through the signature). Unhashable/unknown arg types
are the remaining graph break (per-call eager), and every break is
counted: ``paddle.jit.capture_report()``.
"""
from __future__ import annotations

import functools
import inspect
import threading
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np

import jax.numpy as jnp

from ..framework.tensor import Tensor, apply_op, grad_enabled, no_grad
from ..nn.layer_base import Layer
from ..observability.registry import default_registry
from .functional import functional_call

__all__ = ["to_static", "not_to_static", "StaticFunction", "InputSpec",
           "capture_report", "reset_capture_report", "capture_telemetry"]


class _CaptureTelemetry:
    """Graph-capture telemetry, registry-backed (replaces the bare
    module-global dict): every count is a ``ptpu_jit_*_total`` counter
    in the observability default registry, and ``snapshot()`` /
    ``reset()`` are the public API — tests and dashboards stop
    reaching into module globals. ``bytecode_graph_calls`` counts
    whole-graph captures that needed the SOT bytecode tier
    (opcode_executor.py) after plain tracing failed."""

    _KEYS = {
        "whole_graph_calls":
            "calls served by a whole-graph compiled program",
        "bytecode_graph_calls":
            "whole-graph captures that needed the SOT bytecode tier",
        "partial_graph_calls":
            "calls served by segmented (break-and-resume) capture",
        "partial_segments_run":
            "compiled segments executed by the partial tier",
        "partial_eager_ops":
            "single instructions run eagerly inside partial capture",
        "graph_break_calls":
            "calls that fell back to eager execution",
        "never_trace_calls":
            "calls dispatched eagerly because the function can never "
            "be a graph (generator / coroutine)",
        "cache_hit_calls":
            "calls that reused an existing compiled specialization",
        "compile_calls":
            "specializations built (guard-key misses)",
    }

    def __init__(self):
        reg = default_registry()
        self._c = {k: reg.counter(f"ptpu_jit_{k}_total", d)
                   for k, d in self._KEYS.items()}
        self._break_reasons = reg.counter(
            "ptpu_jit_graph_breaks_total",
            "graph breaks by normalized reason", labels=("reason",))
        self._lock = threading.Lock()
        self._breaks: dict = {}

    def bump(self, key: str, n: int = 1) -> None:
        self._c[key].inc(n)

    def note_break(self, reason: str) -> None:
        self._c["graph_break_calls"].inc()
        # the LABEL is the prefix before ':' so embedded exception text
        # cannot explode label cardinality; the full reason keeps its
        # own exact count in the breaks dict
        self._break_reasons.labels(
            reason=reason.split(":", 1)[0].strip()).inc()
        with self._lock:
            self._breaks[reason] = self._breaks.get(reason, 0) + 1

    def snapshot(self) -> dict:
        out = {k: int(c.value) for k, c in self._c.items()}
        segs, eag = out["partial_segments_run"], out["partial_eager_ops"]
        out["partial_compiled_fraction"] = round(
            segs / (segs + eag), 4) if segs + eag else None
        with self._lock:
            out["breaks"] = dict(self._breaks)
        return out

    def reset(self) -> None:
        for c in self._c.values():
            c.reset()
        self._break_reasons.reset()
        with self._lock:
            self._breaks = {}


capture_telemetry = _CaptureTelemetry()


# Opcodes that REBIND names which always survive the call (module
# globals, closure cells). Functions containing these are routed to
# the strict bytecode tier, where such stores replay every call
# instead of baking at trace time. STORE_ATTR/STORE_SUBSCR are NOT
# scanned: their targets are usually call-local (and instrumentation
# like ``stats["n"] += 1`` is common in hot code) — static scanning
# cannot separate those from caller-owned targets, and demoting every
# such function to the break-prone strict tier would deoptimize far
# more than it fixes. docs/MIGRATION.md scopes the replay guarantee
# accordingly.
_EFFECT_OPNAMES = frozenset({"STORE_GLOBAL", "DELETE_GLOBAL"})
_DEREF_OPNAMES = frozenset({"STORE_DEREF", "DELETE_DEREF"})


def _writes_surviving_state(fn) -> bool:
    import dis
    import types as _types
    target = fn.__func__ if inspect.ismethod(fn) else fn
    if not isinstance(target, _types.FunctionType):
        return False
    # Cells INHERITED from an enclosing scope (co_freevars) outlive the
    # call; the function's OWN cellvars (a local captured by a nested
    # lambda/def — ubiquitous in jax-style code) die with it and must
    # NOT demote the function to the strict tier.
    surviving = set(target.__code__.co_freevars)

    def scan(code) -> bool:
        for ins in dis.get_instructions(code):
            if ins.opname in _EFFECT_OPNAMES:
                return True
            if ins.opname in _DEREF_OPNAMES and ins.argval in surviving:
                return True
        # nested defs/lambdas/comprehensions can store through the
        # same inherited cells (their freevars chain up through the
        # outer function's freevars — `surviving` filters to those)
        return any(isinstance(c, _types.CodeType) and scan(c)
                   for c in code.co_consts)

    try:
        return scan(target.__code__)
    except Exception:
        return True  # unscannable: assume effects, strict tier is safe


def capture_report():
    """``capture_telemetry.snapshot()``: {whole_graph_calls,
    bytecode_graph_calls, partial_*, graph_break_calls,
    never_trace_calls, cache_hit_calls, compile_calls, breaks:
    {reason: count}} accumulated across all StaticFunction calls."""
    return capture_telemetry.snapshot()


def reset_capture_report():
    capture_telemetry.reset()


def _note_break(reason: str):
    capture_telemetry.note_break(reason)


# per-function bound on guard specializations: beyond this, distinct
# static values (e.g. a fresh float each call) evict + recompile, which
# is recorded as a graph break rather than leaking compiled programs
_CACHE_LIMIT = 64

_BROKEN = object()  # cache sentinel: this specialization cannot trace
_NO_PARTIAL = object()  # _try_partial: outside the segmentable envelope


def _static_guard_key(v):
    """Hashable guard for a non-tensor argument, or raise TypeError.
    Containers of guardable values guard on their contents."""
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return (type(v).__name__, v)
    if isinstance(v, (tuple, list)):
        return ("seq", type(v).__name__,
                tuple(_static_guard_key(e) for e in v))
    if isinstance(v, dict):
        return ("dict", tuple(sorted(
            (k, _static_guard_key(val)) for k, val in v.items())))
    if isinstance(v, np.dtype) or (isinstance(v, type)
                                   and issubclass(v, np.generic)):
        return ("dtype", str(v))
    raise TypeError(f"unguardable argument type {type(v).__name__}")


class InputSpec:
    """paddle.static.InputSpec analog (shape with None = dynamic dim)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


class StaticFunction:
    def __init__(self, function: Callable, input_spec=None,
                 build_strategy=None, full_graph=True, backend=None):
        if isinstance(function, Layer):
            self._layer = function
            # vars() not getattr: auto_capture may have REBOUND the
            # class's forward to a StaticFunction (left in place by
            # design) — unwrap to the original function
            fwd = type(function).__dict__.get("forward",
                                              type(function).forward)
            self._fn = fwd._fn if isinstance(fwd, StaticFunction) else fwd
            self._bound_self = function
        elif hasattr(function, "__self__") and isinstance(
                function.__self__, Layer):
            self._layer = function.__self__
            fn = function.__func__
            self._fn = fn._fn if isinstance(fn, StaticFunction) else fn
            self._bound_self = function.__self__
        else:
            self._layer = None
            if isinstance(function, StaticFunction):
                function = function._fn
            self._fn = function
            self._bound_self = None
        self._input_spec = input_spec
        self._cache = {}  # static-guard key -> (tier, jitted program)
        self._overflow_warned = False
        self._partial = None  # SegmentedFunction (tier 3), lazily built
        self._sig = None  # lazily-computed signature (kwargs path)
        # generators/coroutines yield control mid-body — not a graph;
        # always run them eagerly instead of crashing in jit
        self._never_trace = (inspect.isgeneratorfunction(self._fn)
                             or inspect.iscoroutinefunction(self._fn)
                             or inspect.isasyncgenfunction(self._fn))
        # no source => the AST tier would fall through to PLAIN jit
        # tracing, which cannot see side effects (they bake at trace
        # time and silently stop repeating). Start such functions at
        # the bytecode tier, whose strict mode catches them. The same
        # hazard exists for SOURCE-AVAILABLE functions whose bytecode
        # REBINDS surviving names (STORE_GLOBAL, or STORE_DEREF to an
        # inherited cell): the AST tier's plain jit would run the
        # write once at trace time and drop it on cached calls —
        # pre-scan the opcodes and start those at the bytecode tier
        # too. (Attribute/item stores and mutating method calls are
        # deliberately NOT scanned — see _EFFECT_OPNAMES; docs/
        # MIGRATION.md scopes the replay guarantee accordingly.)
        try:
            inspect.getsource(self._fn)
            self._prefer_bytecode = _writes_surviving_state(self._fn)
        except (OSError, TypeError):
            self._prefer_bytecode = True
        functools.update_wrapper(self, self._fn)

    def __get__(self, obj, objtype=None):
        # descriptor protocol: auto_capture rebinds class METHODS to
        # StaticFunction; instance calls must still bind self
        if obj is None:
            return self
        if isinstance(obj, Layer):
            # route through the LAYER path per instance: params/buffers
            # become traced inputs via bind_state, so optimizer updates
            # are seen every call. Baking `self` as a static closure
            # would constant-fold the parameters at trace time — the
            # model would silently stop learning in the compiled path
            # (and guarding the instance is impossible anyway).
            # The per-instance StaticFunction lives ON the instance:
            # the only strong path is obj -> sf -> obj, a plain gc-
            # collectable cycle (a dict on the class would make every
            # instance ever called immortal — r5 review repro).
            attr = "_ptpu_sf_" + getattr(self._fn, "__name__", "fn")
            sf = obj.__dict__.get(attr)
            if sf is None:
                import types
                sf = StaticFunction(types.MethodType(self._fn, obj),
                                    input_spec=self._input_spec)
                object.__setattr__(obj, attr, sf)
            return sf
        return functools.partial(self, obj)

    @property
    def layer(self):
        return self._layer

    def _converted(self):
        # AST pass first (dy2static.py): tensor-dependent if/while/for
        # become lax.cond/while_loop instead of tracer errors; returns
        # the original fn unchanged when conversion isn't possible
        if not getattr(self._fn, "_not_to_static", False):
            from .dy2static import convert_to_static
            return convert_to_static(self._fn)
        return self._fn

    def _split_args(self, args, kwargs):
        """Bind through the signature, then split into (layout,
        dynamic_arrays, static_key). Layout entries rebuild the call as
        (args, kwargs) inside the traced fn — keyword-only params stay
        keywords and *args tuples re-expand positionally. Raises
        TypeError on unguardable values (the caller falls back to
        eager = graph break)."""
        entries = []  # ("pos"|("kw", name), "dyn"|"static", payload)

        def add(dest, v, dyn, skey):
            if isinstance(v, Tensor):
                # keep the TENSOR (not v._data): the training-mode tape
                # path needs the original object so gradients flow to
                # callers upstream of the captured function — r5 review
                # repro: an embedding feeding a captured block silently
                # stopped learning when this held the raw array
                entries.append((dest, "dyn", len(dyn)))
                dyn.append(v)
            elif isinstance(v, (jax.Array, np.ndarray, np.generic)):
                # numpy scalars (np.float32(x)) are dynamic operands,
                # like the arrays they broadcast with
                entries.append((dest, "dyn", len(dyn)))
                dyn.append(np.asarray(v) if isinstance(v, np.generic)
                           else v)
            else:
                skey.append(_static_guard_key(v))
                entries.append((dest, "static", v))

        dyn, skey = [], []
        if kwargs:
            if self._sig is None:
                self._sig = inspect.signature(self._fn)
            sig = self._sig
            ba = sig.bind(*(((self._bound_self,) + args)
                            if self._bound_self is not None else args),
                          **kwargs)
            ba.apply_defaults()
            params = list(sig.parameters.values())
            if self._bound_self is not None:
                params = params[1:]
            for p in params:
                if p.name not in ba.arguments:
                    continue
                v = ba.arguments[p.name]
                if p.kind == p.VAR_POSITIONAL:
                    for e in v:
                        add("pos", e, dyn, skey)
                elif p.kind == p.VAR_KEYWORD:
                    for k2, e in v.items():
                        add(("kw", k2), e, dyn, skey)
                elif p.kind == p.KEYWORD_ONLY:
                    add(("kw", p.name), v, dyn, skey)
                else:
                    add("pos", v, dyn, skey)
        else:
            for v in args:
                add("pos", v, dyn, skey)
        raw = tuple(x._data if isinstance(x, Tensor) else x
                    for x in dyn)
        return tuple(entries), raw, tuple(skey), tuple(dyn)

    def _build(self, layout, bytecode=False):
        layer = self._layer
        if bytecode:
            # SOT tier: interpret the ORIGINAL function's bytecode
            # (tensor-if becomes lax.cond inside the interpreter); used
            # when AST conversion + plain tracing already failed
            from .opcode_executor import OpcodeFunction
            # strict: side effects on objects that outlive the call
            # must not bake at trace time — they GraphBreak, and tier 3
            # replays them eagerly at a segment boundary
            fn = OpcodeFunction(self._fn, strict=True)
        else:
            fn = self._converted()

        def rebuild(arg_arrays):
            pos, kw = [], {}
            for dest, kind, v in layout:
                if kind == "dyn":
                    a = arg_arrays[v]
                    a = Tensor(a) if isinstance(
                        a, (jax.Array, jax.core.Tracer)) else a
                else:
                    a = v
                if dest == "pos":
                    pos.append(a)
                else:
                    kw[dest[1]] = a
            return pos, kw

        if layer is not None:
            def pure(params, buffers, training, *arg_arrays):
                layer.train() if training else layer.eval()
                pos, kw = rebuild(arg_arrays)
                with layer.bind_state(params, buffers):
                    out = fn(layer, *pos, **kw)
                    new_buffers = {n: b._data
                                   for n, b in layer.named_buffers()
                                   if b is not None}
                return _unwrap_tree(out), new_buffers
            return jax.jit(pure, static_argnums=(2,))

        def pure(*arg_arrays):
            pos, kw = rebuild(arg_arrays)
            return _unwrap_tree(fn(*pos, **kw))
        return jax.jit(pure)

    def _eager(self, args, kwargs):
        if self._bound_self is not None:
            return self._fn(self._bound_self, *args, **kwargs)
        return self._fn(*args, **kwargs)

    def _try_partial(self, args, kwargs, key):
        """Tier 3: segmented capture. Returns _NO_PARTIAL when the
        function is outside the segmentable envelope (layer-bound,
        closures, generators) or segmentation itself breaks."""
        from .opcode_executor import GraphBreak
        from .partial_capture import SegmentedFunction, segmentable
        if self._layer is not None or self._bound_self is not None \
                or not segmentable(self._fn):
            return _NO_PARTIAL
        entry = self._partial
        if entry is None:
            try:
                entry = SegmentedFunction(self._fn)
            except GraphBreak:
                return _NO_PARTIAL
            self._partial = entry
        try:
            out = entry(*args, **kwargs)
        except GraphBreak:
            # refusal happens BEFORE any eager op runs (driver design:
            # a mid-call failure raises RuntimeError, never re-runs)
            return _NO_PARTIAL
        self._cache[key] = ("sotp", entry)
        capture_telemetry.bump("partial_graph_calls")
        return out

    def __call__(self, *args, **kwargs):
        from . import _to_static_enabled
        if not _to_static_enabled[0]:
            # enable_to_static(False) passthrough
            return self._eager(args, kwargs)
        if self._never_trace:
            # generator / coroutine function: cannot be a graph
            capture_telemetry.bump("never_trace_calls")
            return self._eager(args, kwargs)
        try:
            layout, dyn, skey, dyn_src = self._split_args(args, kwargs)
        except TypeError as e:
            _note_break(f"unguardable arg: {e}")
            return self._eager(args, kwargs)
        key = (skey, tuple((dest, kind) for dest, kind, _ in layout))
        entry = self._cache.get(key)
        if entry is _BROKEN:
            # this specialization failed tracing before: stay eager
            # without paying a full re-trace per call
            _note_break("known graph break (cached)")
            return self._eager(args, kwargs)
        if entry is not None:
            # LRU refresh so churn on other keys can't evict hot entries
            self._cache.pop(key)
            self._cache[key] = entry
            capture_telemetry.bump("cache_hit_calls")
            tier, jitted = entry
            if tier == "sotp":
                # segmented capture executes with the ORIGINAL call
                # convention (it owns its per-segment jits)
                from .opcode_executor import GraphBreak
                try:
                    out = jitted(*args, **kwargs)
                except GraphBreak as e:
                    # a fresh specialization can refuse (e.g. newly
                    # unsegmentable state before any side effect ran)
                    _note_break(f"partial refused: {e}")
                    return self._eager(args, kwargs)
                capture_telemetry.bump("partial_graph_calls")
                return out
        else:
            if len(self._cache) >= _CACHE_LIMIT:
                # guard explosion (e.g. a fresh float every call):
                # evict least-recently-used, record churn as breaks
                self._cache.pop(next(iter(self._cache)))
                _note_break("guard cache overflow")
                if not self._overflow_warned:
                    self._overflow_warned = True
                    import warnings
                    warnings.warn(
                        f"to_static function "
                        f"{getattr(self._fn, '__name__', '?')!r} exceeded "
                        f"{_CACHE_LIMIT} guard specializations — a "
                        f"non-tensor argument is taking a fresh value "
                        f"every call (step counter, growing length?), "
                        f"forcing a recompile per call. Pass it as a "
                        f"Tensor/array to trace it dynamically.",
                        RuntimeWarning, stacklevel=3)
            if self._prefer_bytecode and self._layer is None:
                from .opcode_executor import interpretable
                tier = "sot" if interpretable(self._fn) else "ast"
            else:
                tier = "ast"
            jitted = self._build(layout, bytecode=(tier == "sot"))
            self._cache[key] = (tier, jitted)
            capture_telemetry.bump("compile_calls")

        def _run(j):
            if self._layer is None:
                return j(*dyn), None, False
            buffers = {n: b._data
                       for n, b in self._layer.named_buffers()
                       if b is not None}
            training = self._layer.training
            params_t = dict(self._layer.named_parameters())
            tape = grad_enabled() and (
                any(not p.stop_gradient for p in params_t.values())
                or any(isinstance(t, Tensor) and not t.stop_gradient
                       for t in dyn_src))
            if not tape:
                params = {n: p._data for n, p in params_t.items()}
                out, new_buffers = j(params, buffers, training, *dyn)
                return out, new_buffers, False
            # TRAINING-mode capture: the compiled program must stay ON
            # the autograd tape — returning detached outputs would make
            # loss.backward() a silent no-op and freeze learning (round
            # 5 regression test). The whole jitted program becomes ONE
            # tape op via apply_op; jax.vjp differentiates through the
            # jit, params/inputs are traced operands every call (never
            # baked constants).
            pnames = list(params_t)
            td_cell = []

            def fwrap(*arrs):
                ps = dict(zip(pnames, arrs[:len(pnames)]))
                out, new_buffers = j(ps, buffers, training,
                                     *arrs[len(pnames):])
                leaves, td = jax.tree.flatten((out, new_buffers))
                td_cell.clear()
                td_cell.append(td)
                return tuple(leaves)

            tensor_args = [params_t[n] for n in pnames] + [
                t if isinstance(t, Tensor) else Tensor(
                    jnp.asarray(t), stop_gradient=True)
                for t in dyn_src]
            res = apply_op(
                fwrap, *tensor_args,
                _op_name=f"to_static[{getattr(self._fn, '__name__', 'fn')}]")
            tensors = list(res) if isinstance(res, (tuple, list)) \
                else [res]
            out, new_buffers = jax.tree.unflatten(td_cell[0], tensors)
            new_buffers = {n: (b._data if isinstance(b, Tensor) else b)
                           for n, b in new_buffers.items()}
            return out, new_buffers, True

        from .opcode_executor import GraphBreak
        _TRACE_ERRS = (GraphBreak,
                       jax.errors.ConcretizationTypeError,
                       jax.errors.TracerArrayConversionError,
                       jax.errors.TracerBoolConversionError,
                       jax.errors.TracerIntegerConversionError)
        try:
            out, new_buffers, wrapped = _run(jitted)
        except _TRACE_ERRS as e:
            if tier == "ast":
                # data-dependent python control flow the AST pass could
                # not lower: escalate to the SOT bytecode tier, which
                # if-converts tensor branches at the opcode level
                try:
                    tier = "sot"
                    jitted = self._build(layout, bytecode=True)
                    capture_telemetry.bump("compile_calls")
                    out, new_buffers, wrapped = _run(jitted)
                    self._cache[key] = (tier, jitted)
                except _TRACE_ERRS as e2:
                    # tier 3: break-and-resume. Compile the prefix,
                    # run the breaking op eagerly, resume capture —
                    # a mid-body break no longer abandons the whole
                    # function (reference _break_graph_when_*).
                    out = self._try_partial(args, kwargs, key)
                    if out is not _NO_PARTIAL:
                        return out
                    self._cache[key] = _BROKEN
                    _note_break(f"graph break: {e2}")
                    return self._eager(args, kwargs)
            else:
                # the sot tier broke — whether freshly built (source-
                # less functions START here) or on a retrace of a
                # cached program: try break-and-resume before eager
                out = self._try_partial(args, kwargs, key)
                if out is not _NO_PARTIAL:
                    return out
                self._cache[key] = _BROKEN
                _note_break(f"trace failure: {type(e).__name__}")
                return self._eager(args, kwargs)
        capture_telemetry.bump("whole_graph_calls")
        if tier == "sot":
            capture_telemetry.bump("bytecode_graph_calls")
        if self._layer is not None:
            with no_grad():
                for n, b in self._layer.named_buffers():
                    if b is not None and n in new_buffers:
                        b._data = new_buffers[n]
            return out if wrapped else _wrap_tree(out)
        return out if wrapped else _wrap_tree(out)

    def concrete_program_specify_input_spec(self, *a, **k):
        return None

    @property
    def code(self):
        import inspect
        return inspect.getsource(self._fn)


def _unwrap_tree(out):
    if isinstance(out, Tensor):
        return out._data
    if isinstance(out, (tuple, list)):
        return tuple(_unwrap_tree(o) for o in out)
    if isinstance(out, dict):
        return {k: _unwrap_tree(v) for k, v in out.items()}
    return out


def _wrap_tree(out):
    if isinstance(out, (jax.Array, np.ndarray)):
        return Tensor(out)
    if isinstance(out, (tuple, list)):
        return tuple(_wrap_tree(o) for o in out)
    if isinstance(out, dict):
        return {k: _wrap_tree(v) for k, v in out.items()}
    return out


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """paddle.jit.to_static analog (decorator or call form)."""
    def decorate(fn):
        return StaticFunction(fn, input_spec, build_strategy,
                              backend=backend)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(function):
    function._not_to_static = True
    return function
