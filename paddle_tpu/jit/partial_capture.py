"""Graph-break-and-resume for the SOT bytecode tier.

Reference behavior: the SOT translator compiles the captured PREFIX
when it cannot continue, executes the breaking construct eagerly, and
RESUMES capture after it
(jit/sot/opcode_translator/executor/opcode_executor.py:1603,
_break_graph_when_if:1801, _break_graph_when_for_loop:2015) — a
mid-body break no longer abandons the whole function to eager.

TPU-native version: the bytecode interpreter (opcode_executor.py) runs
the function as a chain of SEGMENTS. Each segment is the maximal
instruction range that traces cleanly; it is replayed under ``jax.jit``
as a pure function of the frame's tensor leaves (everything else is
pinned by the cache key). The breaking instruction between segments
executes EAGERLY on real values — where a tensor ``bool`` is an
ordinary Python bool and side effects are plain Python — and capture
resumes at the next pc. A bytecode-level tensor ``while`` therefore
runs as one compiled segment per iteration with only the loop
condition eager, instead of abandoning the function.

Scope (falls back to whole-function eager outside it): functions
without closure cells, with hashable non-tensor frame state at segment
boundaries, and non-generator code objects. Like every to_static
capture in this repo, outputs are DETACHED — differentiate inside the
captured program (TrainStep pattern), not through it. Mutable
containers that are ALIASED in frame state refuse segmentation (the
pytree round-trip would split the aliases); live iterators likewise.
"""
from __future__ import annotations

import inspect
import types
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from .opcode_executor import (GraphBreak, OpcodeExecutor, _Frame,
                              _State, _STOPPED, _GEN_FLAGS)

__all__ = ["SegmentedFunction", "segmentable"]


class _AliasedState(Exception):
    """Segment END state aliases a mutable container: crossing the
    jit boundary would split the aliases — run the range eagerly."""

_MAX_SEGMENTS_PER_CALL = 512   # past this, finish eagerly (no abort)
_MAX_CACHED_SEGMENTS = 128     # per function; beyond: eager-step only
_MISSING_GLOBAL = object()     # guard token for an unbound global name


def _has_aliased_mutables(state) -> bool:
    """True when any mutable container is reachable TWICE."""
    seen = set()

    def walk(v):
        if isinstance(v, (list, dict, set, bytearray)):
            if id(v) in seen:
                return True
            seen.add(id(v))
        if isinstance(v, dict):
            return any(walk(x) for x in v.values())
        if isinstance(v, (list, tuple)):
            return any(walk(x) for x in v)
        return False

    return walk(list(state))


def _mutable_ids(obj, acc=None) -> frozenset:
    """ids of every mutable container reachable from ``obj``."""
    if acc is None:
        acc = set()
    if isinstance(obj, (list, dict, set, bytearray)):
        if id(obj) in acc:
            return acc
        acc.add(id(obj))
    if isinstance(obj, dict):
        for v in obj.values():
            _mutable_ids(v, acc)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _mutable_ids(v, acc)
    return acc


def _contains_ids(state, ids) -> bool:
    if not ids:
        return False

    def walk(v):
        if id(v) in ids:
            return True
        if isinstance(v, dict):
            return any(walk(x) for x in v.values())
        if isinstance(v, (list, tuple)):
            return any(walk(x) for x in v)
        return False

    return walk(list(state))


def segmentable(fn) -> bool:
    target = fn.__func__ if isinstance(fn, types.MethodType) else fn
    if not isinstance(target, types.FunctionType):
        return False
    code = target.__code__
    return not (code.co_flags & _GEN_FLAGS) \
        and not code.co_cellvars and not code.co_freevars


def _is_tensorish(v) -> bool:
    from ..framework.tensor import Tensor
    return isinstance(v, (Tensor, jax.Array, jax.core.Tracer))


def _flatten_vals(vals):
    """(leaves, treedef, wrapped-flags): tensor leaves come out as raw
    jax arrays; every other leaf is 'static'."""
    from ..framework.tensor import Tensor
    leaves, treedef = jax.tree.flatten(
        vals, is_leaf=lambda x: isinstance(x, Tensor))
    dyn, static, spec = [], [], []
    for l in leaves:
        if _is_tensorish(l):
            spec.append("T" if isinstance(l, Tensor) else "A")
            dyn.append(l._data if isinstance(l, Tensor) else l)
        else:
            spec.append(None)
            static.append(l)
    return dyn, static, tuple(spec), treedef


def _unflatten_vals(dyn, static, spec, treedef):
    from ..framework.tensor import Tensor
    dyn_it = iter(dyn)
    st_it = iter(static)
    leaves = []
    for s in spec:
        if s is None:
            leaves.append(next(st_it))
        elif s == "T":
            leaves.append(Tensor(next(dyn_it)))
        else:
            leaves.append(next(dyn_it))
    return jax.tree.unflatten(treedef, leaves)


def _hashable(x) -> bool:
    try:
        hash(x)
        return True
    except TypeError:
        return False


class SegmentedFunction:
    """Callable that runs ``fn``'s bytecode as compiled segments with
    eager breaking ops between them (see module docstring)."""

    def __init__(self, fn: Callable):
        if isinstance(fn, types.MethodType):
            self._self = fn.__self__
            fn = fn.__func__
        else:
            self._self = None
        if not isinstance(fn, types.FunctionType):
            raise GraphBreak(f"not a Python function: {fn!r}")
        if not segmentable(fn):
            raise GraphBreak("not segmentable (cells/generator)")
        # static pre-check: EVERY opcode must have a handler, so the
        # driver can never die mid-call on an unknown op after side
        # effects already ran (it could not safely re-run eagerly)
        from .opcode_executor import instructions_sans_caches
        for ins in instructions_sans_caches(fn.__code__):
            if not hasattr(OpcodeExecutor, "_op_" + ins.opname):
                raise GraphBreak(
                    f"unsupported opcode {ins.opname} (pre-check)")
        self.fn = fn
        # (start_pc, static_key, avals) -> segment record
        self._segments: Dict[Tuple, Tuple] = {}
        # Global reads are trace-time constants inside a compiled
        # segment, but this tier exists for SIDE-EFFECTING functions —
        # where a baked read feeds a replayed write (``G = G + 1``
        # would re-store the trace-time G+1 forever). Guard segment
        # keys on the current values of every name the bytecode
        # LOAD_GLOBALs: a changed global re-specializes the segment
        # (bounded by _MAX_CACHED_SEGMENTS, past which the driver
        # eager-steps — correct, and self-limiting for globals that
        # change every call).
        import dis
        self._global_names = tuple(sorted({
            ins.argval for ins in dis.get_instructions(fn.__code__)
            if ins.opname == "LOAD_GLOBAL"}))

    def _globals_guard(self):
        toks = []
        g = self.fn.__globals__
        for name in self._global_names:
            v = g.get(name, _MISSING_GLOBAL)
            if isinstance(v, (int, float, bool, str, bytes,
                              type(None))):
                toks.append((name, type(v).__name__, v))
            else:
                # objects (modules, functions, classes): identity-
                # stable in practice; id() keys re-binding, not
                # interior mutation (interior mutation of a read-only
                # global is out of scope, as in the reference SOT)
                toks.append((name, "id", id(v)))
        return tuple(toks)

    # -- frame state <-> pytree -------------------------------------------
    def _snapshot(self, f: _Frame):
        # kwnames rides along: a boundary between KW_NAMES and CALL
        # must not drop it (it is a static tuple of strings)
        return (list(f.stack), list(f.locals), f.kwnames)

    def _segment_key(self, pc: int, state, arg_mut_ids=frozenset()):
        if _contains_ids(state, arg_mut_ids):
            # a mutable container the CALLER holds a reference to: the
            # pytree round-trip at a boundary would rebuild it as a new
            # object, so post-boundary mutations would miss the
            # caller's copy — eager-step instead
            return None, None
        if _has_aliased_mutables(state):
            # the pytree round-trip would materialize aliases as
            # SEPARATE objects; post-boundary mutations would miss the
            # other name — eager-step instead (correctness first)
            return None, None
        dyn, static, spec, treedef = _flatten_vals(state)
        for s in static:
            if not _hashable(s):
                return None, None
            if hasattr(s, "__next__"):
                # a live iterator in frame state is STATEFUL: baking it
                # into a compiled segment would consume it at trace
                # time and replay exhausted — eager-step instead
                return None, None
        avals = tuple((tuple(a.shape), str(a.dtype)) for a in dyn)
        return (pc, tuple(static), spec, treedef, avals,
                self._globals_guard()), dyn

    # -- one segment ------------------------------------------------------
    def _discover(self, pc: int, state, dyn):
        """Trace from ``pc`` to find where (or whether) capture breaks,
        then build the jitted replay for the clean range."""
        _, static, spec, treedef = _flatten_vals(state)
        probe_ex = [None]

        def replay(dyn_in, stop_pc):
            ex = OpcodeExecutor(self.fn.__code__, self.fn.__globals__,
                                None, _State(strict=True))
            probe_ex[0] = ex
            stack, locals_, kwn = _unflatten_vals(dyn_in, static,
                                                  spec, treedef)
            f = _Frame.__new__(_Frame)
            f.stack = list(stack)
            f.locals = list(locals_)
            f.cells = []
            f.pc = pc
            f.kwnames = tuple(kwn)
            r = ex._execute(f, stop_pc=stop_pc)
            if r is _STOPPED:
                snap = self._snapshot(f)
                if _has_aliased_mutables(snap):
                    raise _AliasedState()
                return ("stopped", snap, f.pc)
            return ("returned", r)

        # discovery trace: does the rest of the function capture whole?
        stop_pc = None
        static_out = {}

        def traced(dyn_in, _stop=None):
            r = replay(dyn_in, _stop)
            if r[0] == "returned":
                dyn_o, st_o, sp_o, td_o = _flatten_vals(r[1])
                static_out["v"] = ("returned", st_o, sp_o, td_o)
                return dyn_o
            dyn_o, st_o, sp_o, td_o = _flatten_vals(r[1])
            static_out["v"] = ("stopped", st_o, sp_o, td_o, r[2])
            return dyn_o

        try:
            jitted = jax.jit(lambda d: traced(d, None))
            out = jitted(dyn)   # traces now; may GraphBreak
            return ("run", jitted, dict(static_out)), out
        except GraphBreak:
            ex = probe_ex[0]
            stop_pc = ex.last_break_pc if ex is not None else None
            if stop_pc is None:
                raise
        if stop_pc == pc:
            # the very first op breaks: nothing to compile here
            return ("eager-op", None, None), None
        static_out.clear()
        try:
            jitted = jax.jit(lambda d: traced(d, stop_pc))
            out = jitted(dyn)
        except _AliasedState:
            return ("eager-op", None, None), None
        return ("run", jitted, dict(static_out)), out

    # -- driver -----------------------------------------------------------
    def __call__(self, *args, **kwargs):
        from .static_function import capture_telemetry
        fn = self.fn
        if self._self is not None:
            args = (self._self,) + args
        try:
            ba = inspect.signature(fn).bind(*args, **kwargs)
        except TypeError as e:
            raise GraphBreak(f"bad call signature: {e}")
        ba.apply_defaults()
        eager_state = _State()
        eager_ex = OpcodeExecutor(fn.__code__, fn.__globals__, None,
                                  eager_state)
        f = eager_ex.make_frame(dict(ba.arguments))
        # mutable containers the CALLER can still see (argument-
        # reachable): crossing a jit boundary must never clone them
        arg_mut_ids = frozenset(_mutable_ids(list(ba.arguments.values())))
        segments_run = 0
        while True:
            segments_run += 1
            # Past the cap (a pathological number of boundaries), stop
            # compiling and FINISH the call with eager interpretation:
            # side effects already happened, so aborting to a whole-
            # function eager re-run would repeat them.
            overloaded = segments_run > _MAX_SEGMENTS_PER_CALL
            key = dyn = None
            if not overloaded:
                key, dyn = self._segment_key(
                    f.pc, (f.stack, f.locals, f.kwnames), arg_mut_ids)
            rec = None
            if key is not None:
                rec = self._segments.get(key)
                if rec is None and \
                        len(self._segments) < _MAX_CACHED_SEGMENTS:
                    try:
                        rec, out = self._discover(
                            f.pc, (f.stack, f.locals, f.kwnames), dyn)
                        self._segments[key] = rec
                    except GraphBreak:
                        rec = ("eager-op", None, None)
                        self._segments[key] = rec
                elif rec is not None:
                    out = rec[1](dyn) if rec[0] == "run" else None
            if rec is None or rec[0] == "eager-op":
                # unsegmentable state or an op that refuses to trace:
                # run ONE instruction eagerly and resume capture
                capture_telemetry.bump("partial_eager_ops")
                try:
                    r = eager_ex._step(f)
                except GraphBreak as e:
                    # cannot continue AND cannot re-run (side effects
                    # already happened): surface loudly, never twice
                    raise RuntimeError(
                        f"partial capture aborted mid-call at pc "
                        f"{f.pc}: {e}") from e
                if r is None:
                    f.pc += 1
                elif isinstance(r, tuple):
                    return r[0]
                continue
            kind = rec[2]["v"][0]
            capture_telemetry.bump("partial_segments_run")
            if kind == "returned":
                _, st_o, sp_o, td_o = rec[2]["v"]
                return _unflatten_vals(list(out), st_o, sp_o, td_o)
            _, st_o, sp_o, td_o, next_pc = rec[2]["v"]
            stack, locals_, kwn = _unflatten_vals(list(out), st_o,
                                                  sp_o, td_o)
            f.stack = list(stack)
            f.locals = list(locals_)
            f.kwnames = tuple(kwn)
            f.pc = next_pc
