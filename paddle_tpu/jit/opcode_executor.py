"""SOT bytecode tier: a symbolic CPython 3.12 opcode interpreter.

Reference analog: the SOT opcode translator + PEP-523 eval-frame hook
(python/paddle/jit/sot/opcode_translator/executor/opcode_executor.py:1603,
paddle/fluid/pybind/sot/eval_frame.c). The reference intercepts every
frame in C and symbolically executes bytecode to build a graph, breaking
where it cannot. TPU-native version: jax.jit tracing already captures
arbitrary Python — the ONLY captures tracing cannot do are (a) Python
branches on *tensor values* (TracerBoolConversionError) and (b)
functions whose source the AST pass cannot get (lambdas defined in a
REPL, exec'd code, decorated closures). This interpreter runs the
function's bytecode instruction-by-instruction with the real runtime
values (tracers during jit tracing), so:

  * tensor-valued ``if`` conditions are IF-CONVERTED at the bytecode
    level: the machine forks, interprets both arms to RETURN, and
    merges the two return values with ``lax.cond`` — no source needed;
  * every other opcode delegates to the real Python object protocol,
    so containers, closures, f-strings, ``with`` blocks and nested
    calls behave exactly as in eager;
  * a callee that itself branches on a tensor is interpreted
    recursively (the tracer error never escapes to the user);
  * anything outside the supported envelope raises ``GraphBreak``,
    which the caller (jit/static_function.py) turns into SEGMENTED
    capture (partial_capture.py: compile the prefix, run the breaking
    op eagerly, resume) or, failing that, whole-function eager —
    never a wrong answer;
  * under ``strict`` state (any jit-traced run), mutations of objects
    that OUTLIVE the call also GraphBreak: a traced side effect would
    execute once at trace time and never again on cached runs — the
    segment boundary replays it eagerly every call instead.

Tensor-valued ``while``: the AST tier lowers source-available ones to
lax.while_loop; at the bytecode level the segmented tier runs the body
as a compiled segment per iteration with only the condition eager.
"""
from __future__ import annotations

import dis
import inspect
import operator
import types
from typing import Callable, Optional

import jax

__all__ = ["GraphBreak", "OpcodeFunction", "interpretable"]

_MAX_INSTRUCTIONS = 200_000   # runaway-loop guard per call
_MAX_FORKS = 16               # nested tensor-if forks per call
_MAX_CALL_DEPTH = 8           # recursive interpretation of callees

_GEN_FLAGS = 0x20 | 0x80 | 0x200  # generator | coroutine | async-gen


class GraphBreak(Exception):
    """Raised when the bytecode cannot be captured; caller goes eager."""


class _State:
    """Execution state shared across forks and recursive callees.

    ``fork_depth`` > 0 means a tensor-``if`` fork is active: BOTH arms
    execute under trace, so a mutation of any object that outlives the
    call (a global, a closure cell, an attribute target, anything that
    escaped) would leak the untaken arm's side effects into real Python
    state — eager runs exactly one arm (ADVICE r3, high).

    The side-effect policy is therefore:

      * objects CREATED during this call ("fresh": containers from
        BUILD_* opcodes, vetted constructor calls, iterators) are
        call-local — each fork arm receives its own deep copy of the
        fresh objects reachable from the frame (``_copy_fresh``), so
        arms can mutate them freely without seeing each other or
        touching the originals;
      * a fresh object DEMOTES (stops being fresh) the moment it could
        escape: stored into a non-fresh target, or passed as an
        argument to an un-vetted native callee;
      * everything else GraphBreaks on mutation while a fork is active
        — the whole call falls back to eager, which is always correct.

    ``fresh`` maps id(obj) -> (obj, fork-epoch at creation). Keeping
    the object reference both pins the id (no reuse) and lets the fork
    copier find the object. Mutation under a fork is allowed only for
    objects created (or copied) under the CURRENT innermost fork epoch.
    """

    __slots__ = ("instructions", "forks", "epochs", "serial", "fresh",
                 "strict")

    def __init__(self, instructions=_MAX_INSTRUCTIONS, forks=_MAX_FORKS,
                 strict=False):
        self.instructions = instructions
        self.forks = forks
        self.epochs: list = []   # stack of active fork serials
        self.serial = 0
        self.fresh: dict = {}    # id(obj) -> (obj, epoch at creation)
        # strict: this execution is a jit TRACE of the whole call —
        # a mutation of anything that outlives the call would run at
        # trace time ONCE and then never again on cached executions,
        # silently dropping repeat side effects. Strict mode breaks
        # instead; the partial-capture tier turns the break into a
        # segment boundary whose op replays eagerly EVERY call.
        self.strict = strict

    @property
    def fork_depth(self) -> int:
        return len(self.epochs)

    def push_fork(self):
        self.serial += 1
        self.epochs.append(self.serial)

    def pop_fork(self):
        self.epochs.pop()

    def _epoch(self) -> int:
        return self.epochs[-1] if self.epochs else 0

    def mark_fresh(self, obj):
        self.fresh[id(obj)] = (obj, self._epoch())

    def is_fresh(self, obj) -> bool:
        e = self.fresh.get(id(obj))
        return e is not None and e[0] is obj

    def is_fresh_current(self, obj) -> bool:
        e = self.fresh.get(id(obj))
        return e is not None and e[0] is obj and e[1] == self._epoch()

    def demote(self, obj):
        """Remove obj (and, recursively, fresh members) from fresh —
        it may now be reachable from state that outlives the call."""
        e = self.fresh.pop(id(obj), None)
        if e is None or e[0] is not obj:
            if e is not None:
                self.fresh[id(obj)] = e  # id collision: put it back
            return
        if isinstance(obj, dict):
            for v in list(obj.values()):
                self.demote(v)
        elif isinstance(obj, (list, tuple, set, frozenset)):
            for v in list(obj):
                self.demote(v)

    def guard_mutation(self, obj, what: str):
        """GraphBreak unless mutating ``obj`` is safe to capture."""
        if self.epochs and not self.is_fresh_current(obj):
            raise GraphBreak(
                f"{what} on a pre-fork object inside a tensor-if arm "
                "(side effect would leak into the untaken branch)")
        if self.strict and not self.is_fresh(obj):
            raise GraphBreak(
                f"{what} on an object that outlives the call (a traced "
                "side effect would run once, not per call)")

    def copy_fresh_into(self, frame):
        """Give a fork arm its own copies of the fresh objects reachable
        from the frame, registered under the new fork epoch. Preserves
        aliasing within the frame; uncopyable fresh objects (iterators)
        stay shared and keep their old epoch, so mutating/advancing
        them inside the arm GraphBreaks."""
        memo: dict = {}

        def cp(v):
            vid = id(v)
            if vid in memo:
                return memo[vid]
            if not self.is_fresh(v):
                return v
            if isinstance(v, list):
                c = []
                memo[vid] = c
                c.extend(cp(x) for x in v)
            elif isinstance(v, dict):
                c = {}
                memo[vid] = c
                for k, x in v.items():
                    c[k] = cp(x)
            elif isinstance(v, set):
                c = set(v)
                memo[vid] = c
            elif isinstance(v, bytearray):
                c = bytearray(v)
                memo[vid] = c
            elif isinstance(v, tuple):
                # tuples are immutable but may ALIAS fresh containers;
                # copy so each arm reaches its own members (interpreted
                # code cannot build self-referential tuples, so the
                # post-build memo entry is safe)
                c = tuple(cp(x) for x in v)
                memo[vid] = c
            else:
                return v
            self.mark_fresh(c)
            return c

        frame.stack = [cp(v) for v in frame.stack]
        frame.locals = [cp(v) for v in frame.locals]


class _Null:
    """CPython's internal NULL stack sentinel (PUSH_NULL et al.)."""
    __slots__ = ()

    def __repr__(self):
        return "<NULL>"


_NULL = _Null()
_JUMPED = object()   # handler already set pc
_UNBOUND = object()  # empty local slot
_STOPPED = object()  # _execute reached stop_pc (partial capture)

_BIN_OPS = {
    "+": operator.add, "-": operator.sub, "*": operator.mul,
    "/": operator.truediv, "//": operator.floordiv, "%": operator.mod,
    "**": operator.pow, "@": operator.matmul, "<<": operator.lshift,
    ">>": operator.rshift, "&": operator.and_, "|": operator.or_,
    "^": operator.xor,
    "+=": operator.iadd, "-=": operator.isub, "*=": operator.imul,
    "/=": operator.itruediv, "//=": operator.ifloordiv,
    "%=": operator.imod, "**=": operator.ipow, "@=": operator.imatmul,
    "<<=": operator.ilshift, ">>=": operator.irshift,
    "&=": operator.iand, "|=": operator.ior, "^=": operator.ixor,
}

_CMP_OPS = {
    "<": operator.lt, "<=": operator.le, "==": operator.eq,
    "!=": operator.ne, ">": operator.gt, ">=": operator.ge,
}


# -- call vetting under a tensor-if fork (ADVICE r3 high) ----------------
# Object kinds whose native call is allowed while both fork arms run.
_PURE_BUILTINS = frozenset({
    len, abs, min, max, sum, sorted, reversed, range, enumerate, zip,
    isinstance, issubclass, getattr, hasattr, repr, format, all, any,
    divmod, round, pow, ord, chr, callable, iter, hash, vars,
})
_FORBIDDEN_BUILTINS = frozenset({
    print, input, exec, eval, setattr, delattr, open, __import__,
    globals, locals, compile, breakpoint,
})
_CTOR_TYPES = frozenset({
    list, dict, set, tuple, frozenset, str, int, float, bool, complex,
    bytes, bytearray, slice, object, type,
})
_FRESH_TYPES = (list, dict, set, bytearray)
# iterables whose iterator protocol runs no user Python
_SAFE_ITERABLES = (list, tuple, dict, set, frozenset, str, bytes,
                   bytearray, range)
_MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "pop", "remove", "clear", "add",
    "discard", "update", "setdefault", "popitem", "sort", "reverse",
    "__setitem__", "__delitem__", "__iadd__", "__ior__", "__iand__",
    "__ixor__", "__isub__", "__imul__", "send", "throw", "close",
})
# in-place only on ndarrays — str.partition / bytes.fill etc. are pure
_NDARRAY_MUTATING_METHODS = frozenset({
    "fill", "partition", "put", "resize", "setflags", "itemset",
    "byteswap", "sort",
})
_TRUSTED_MODULE_PREFIXES = (
    "jax", "numpy", "math", "cmath", "operator", "itertools", "einops",
    "paddle_tpu.ops", "paddle_tpu.nn.functional",
    "paddle_tpu.tensor_module", "paddle_tpu.linalg", "paddle_tpu.fft",
    "paddle_tpu.signal", "paddle_tpu.framework.tensor",
    "paddle_tpu.framework.dtype",
)


def _trusted_module(mod) -> bool:
    """Functional-API modules whose calls are side-effect-free."""
    if not mod:
        return False
    return any(mod == p or mod.startswith(p + ".")
               for p in _TRUSTED_MODULE_PREFIXES)


def _unwrap_partials(func):
    import functools
    while isinstance(func, functools.partial):
        func = func.func
    return func


def _safe_in(obj, s) -> bool:
    """Membership test that treats unhashable objects as absent."""
    try:
        return obj in s
    except TypeError:
        return False


def _is_mutating_method(name: str, self_obj) -> bool:
    if name in _MUTATING_METHODS:
        return True
    return name in _NDARRAY_MUTATING_METHODS \
        and type(self_obj).__module__ == "numpy"


# callees that consume the iteration protocol of their arguments
_ITERATING_BUILTINS = frozenset({
    iter, reversed, enumerate, zip, sorted, sum, min, max, any, all,
})


def _fork_iter_safe(a) -> bool:
    """May this value be handed to an iterating callee while a fork is
    active? True only when its iteration protocol runs no user Python."""
    return isinstance(a, _SAFE_ITERABLES) or _is_tensorish(a) \
        or isinstance(a, (int, float, bool, complex, type(None))) \
        or type(a).__module__ == "builtins"


def _is_tensorish(v) -> bool:
    from ..framework.tensor import Tensor
    return isinstance(v, (Tensor, jax.Array, jax.core.Tracer))


def _as_array(v):
    from ..framework.tensor import Tensor
    return v._data if isinstance(v, Tensor) else v


def _concrete_bool(v) -> Optional[bool]:
    """bool(v) if that does not depend on a traced value, else None."""
    if _is_tensorish(v):
        try:
            return bool(_as_array(v))
        except jax.errors.TracerBoolConversionError:
            return None
    return bool(v)


class _Frame:
    """Mutable machine state; cheap to fork for if-conversion."""

    __slots__ = ("stack", "locals", "cells", "pc", "kwnames")

    def __init__(self, nlocals, ncells):
        self.stack: list = []
        self.locals: list = [_UNBOUND] * nlocals
        self.cells: list = [None] * ncells
        self.pc = 0
        self.kwnames: tuple = ()

    def fork(self) -> "_Frame":
        f = _Frame.__new__(_Frame)
        f.stack = list(self.stack)
        f.locals = list(self.locals)
        # The cells LIST is copied so MAKE_CELL in one arm cannot bind a
        # cell the other arm sees; the CellType objects themselves stay
        # shared for reads, and STORE_DEREF GraphBreaks while forked.
        f.cells = list(self.cells)
        f.pc = self.pc
        f.kwnames = self.kwnames
        return f


def instructions_sans_caches(code):
    """dis.get_instructions without CACHE entries, on every CPython:
    3.11+ takes show_caches=False; 3.10 has no CACHE slots (and no
    kwarg) so the plain call is already cache-free."""
    try:
        return list(dis.get_instructions(code, show_caches=False))
    except TypeError:
        return list(dis.get_instructions(code))


class OpcodeExecutor:
    """Interprets one code object with concrete/traced values."""

    def __init__(self, code: types.CodeType, fglobals: dict,
                 closure: Optional[tuple], state: _State,
                 call_depth: int = 0):
        if code.co_flags & _GEN_FLAGS:
            raise GraphBreak("generator/coroutine bytecode")
        self.code = code
        self.globals = fglobals
        self.closure = closure or ()
        self.state = state  # shared across forks and callees
        self.call_depth = call_depth
        self.last_break_pc: Optional[int] = None
        self.instrs = instructions_sans_caches(code)
        self.off2idx = {i.offset: n for n, i in enumerate(self.instrs)}

    # -- entry ------------------------------------------------------------
    def make_frame(self, bound_args: dict) -> "_Frame":
        """Frame with parameters bound (defaults applied by caller)."""
        code = self.code
        f = _Frame(code.co_nlocals,
                   len(code.co_cellvars) + len(code.co_freevars))
        nargs = code.co_argcount + code.co_kwonlyargcount
        for i, name in enumerate(code.co_varnames[:nargs]):
            if name in bound_args:
                f.locals[i] = bound_args[name]
        slot = nargs
        if code.co_flags & 0x04:  # *args
            name = code.co_varnames[slot]
            f.locals[slot] = tuple(bound_args.get(name, ()))
            slot += 1
        if code.co_flags & 0x08:  # **kwargs
            name = code.co_varnames[slot]
            kw = dict(bound_args.get(name, {}))
            self.state.mark_fresh(kw)
            f.locals[slot] = kw
        return f

    def run(self, bound_args: dict):
        """bound_args: parameter name -> value (defaults applied)."""
        return self._execute(self.make_frame(bound_args))

    # -- main loop --------------------------------------------------------
    def _execute(self, f: _Frame, stop_pc: Optional[int] = None):
        """Interpret to RETURN; with ``stop_pc``, stop (and return the
        sentinel ``_STOPPED``) when that instruction index is reached
        AFTER at least one step — the partial-capture driver replays a
        discovered segment up to (not including) its breaking op."""
        instrs = self.instrs
        n = len(instrs)
        steps = 0
        while True:
            if stop_pc is not None and f.pc == stop_pc and steps > 0:
                return _STOPPED
            if f.pc >= n:
                raise GraphBreak("fell off code end")
            self.state.instructions -= 1
            if self.state.instructions <= 0:
                raise GraphBreak("instruction budget exhausted "
                                 "(unbounded loop under trace?)")
            ins = instrs[f.pc]
            steps += 1
            try:
                r = self._step(f, ins)
            except GraphBreak:
                # where the capture broke — the partial-capture driver
                # turns this pc into a segment boundary
                self.last_break_pc = f.pc
                raise
            if r is None:
                f.pc += 1
            elif r is _JUMPED:
                pass
            else:
                return r[0]

    def _step(self, f: _Frame, ins=None):
        """Execute exactly one instruction; returns the handler result
        (None = fall through, _JUMPED, or a 1-tuple return value)."""
        if ins is None:
            ins = self.instrs[f.pc]
        handler = getattr(self, "_op_" + ins.opname, None)
        if handler is None:
            raise GraphBreak(f"unsupported opcode {ins.opname}")
        try:
            return handler(f, ins)
        except GraphBreak:
            raise
        except jax.errors.TracerBoolConversionError:
            raise GraphBreak(
                f"tensor bool outside a branch ({ins.opname})")
        except (jax.errors.TracerArrayConversionError,
                jax.errors.TracerIntegerConversionError,
                jax.errors.ConcretizationTypeError) as e:
            # float()/int()/np.asarray() on a traced value: fine in
            # eager, impossible under trace — a segment boundary
            raise GraphBreak(
                f"tensor concretization in {ins.opname}: "
                f"{type(e).__name__}")

    def _jump(self, f: _Frame, target_offset: int):
        try:
            f.pc = self.off2idx[target_offset]
        except KeyError:
            raise GraphBreak(f"jump to unknown offset {target_offset}")

    # -- if-conversion ----------------------------------------------------
    def _if_convert(self, f: _Frame, cond, jump_offset: int,
                    jump_when: bool):
        """Fork on a traced bool: run the fallthrough and jump paths
        each to RETURN, merge the returns with lax.cond. ``jump_when``
        is the condition value that takes the jump."""
        self.state.forks -= 1
        if self.state.forks <= 0:
            raise GraphBreak("too many tensor-branch forks")
        taken = f.fork()
        self._jump(taken, jump_offset)
        fall = f.fork()
        fall.pc += 1
        self.state.push_fork()
        try:
            # each arm mutates its OWN copies of call-local objects;
            # the originals (and the other arm) never see the effects
            self.state.copy_fresh_into(taken)
            out_taken = self._execute(taken)
            self.state.copy_fresh_into(fall)
            out_fall = self._execute(fall)
        finally:
            self.state.pop_fork()

        import jax.numpy as jnp
        from ..framework.tensor import Tensor

        def _flat(out):
            leaves, treedef = jax.tree.flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            return leaves, treedef

        lt, tt = _flat(out_taken)
        lf, tf = _flat(out_fall)
        if tt != tf or len(lt) != len(lf):
            raise GraphBreak("tensor-if arms return different structures")
        # partition: identical non-tensor leaves pass through untouched;
        # everything else must be array-convertible with matching
        # shape/dtype and is merged through the cond
        sel = []       # indices merged via cond
        merged = list(lt)
        for i, (a, b) in enumerate(zip(lt, lf)):
            if not _is_tensorish(a) and not _is_tensorish(b):
                if a is b:
                    continue
                same = False
                if type(a) is type(b):
                    # __eq__ may raise or return a non-bool (numpy
                    # arrays): any such leaf counts as "differing" and
                    # falls through to the GraphBreak below, never to
                    # a user-visible crash
                    try:
                        same = bool(a == b)
                    except Exception:
                        same = False
                if same:
                    continue
                if not isinstance(a, (bool, int, float)) \
                        or not isinstance(b, (bool, int, float)):
                    raise GraphBreak(
                        "tensor-if arms return differing non-tensor "
                        f"values: {a!r} vs {b!r}")
            sel.append(i)
        wrapped = [isinstance(lt[i], Tensor) for i in sel]
        ta = [jnp.asarray(_as_array(lt[i])) for i in sel]
        fa = [jnp.asarray(_as_array(lf[i])) for i in sel]
        for a, b in zip(ta, fa):
            if a.shape != b.shape:
                raise GraphBreak("tensor-if arms return different "
                                 f"shapes: {a.shape} vs {b.shape}")
        pred = jnp.asarray(_as_array(cond))
        if jump_when:  # jump path is the True branch
            out = jax.lax.cond(pred, lambda: ta, lambda: fa)
        else:
            out = jax.lax.cond(pred, lambda: fa, lambda: ta)
        for i, v, w in zip(sel, out, wrapped):
            merged[i] = Tensor(v) if w else v
        return (jax.tree.unflatten(tt, merged),)

    def _branch(self, f: _Frame, ins, jump_when: bool):
        v = f.stack.pop()
        b = _concrete_bool(v)
        if b is None:
            return self._if_convert(f, v, ins.argval, jump_when)
        if b == jump_when:
            self._jump(f, ins.argval)
        else:
            f.pc += 1
        return _JUMPED

    # -- opcode handlers --------------------------------------------------
    # Return None to fall through, _JUMPED if pc was set, or a 1-tuple
    # (value,) to return from the frame.

    def _op_RESUME(self, f, ins):
        pass

    def _op_NOP(self, f, ins):
        pass

    def _op_CACHE(self, f, ins):
        pass

    def _op_EXTENDED_ARG(self, f, ins):
        pass

    def _op_LOAD_CONST(self, f, ins):
        f.stack.append(ins.argval)

    def _op_RETURN_CONST(self, f, ins):
        return (ins.argval,)

    def _op_RETURN_VALUE(self, f, ins):
        return (f.stack.pop(),)

    def _op_LOAD_FAST(self, f, ins):
        v = f.locals[ins.arg]
        if v is _UNBOUND:
            raise GraphBreak(f"unbound local {ins.argval!r}")
        f.stack.append(v)

    _op_LOAD_FAST_CHECK = _op_LOAD_FAST

    def _op_LOAD_FAST_AND_CLEAR(self, f, ins):
        v = f.locals[ins.arg]
        f.stack.append(None if v is _UNBOUND else v)
        f.locals[ins.arg] = _UNBOUND

    def _op_STORE_FAST(self, f, ins):
        f.locals[ins.arg] = f.stack.pop()

    def _op_DELETE_FAST(self, f, ins):
        f.locals[ins.arg] = _UNBOUND

    def _op_LOAD_GLOBAL(self, f, ins):
        if ins.arg & 1:
            f.stack.append(_NULL)
        name = ins.argval
        if name in self.globals:
            f.stack.append(self.globals[name])
        else:
            import builtins
            try:
                f.stack.append(getattr(builtins, name))
            except AttributeError:
                raise GraphBreak(f"NameError: {name}")

    def _op_STORE_GLOBAL(self, f, ins):
        if self.state.fork_depth > 0 or self.state.strict:
            raise GraphBreak(
                "global store under capture (side effect would bake "
                "at trace time)")
        v = f.stack.pop()
        self.state.demote(v)
        self.globals[ins.argval] = v

    def _op_PUSH_NULL(self, f, ins):
        f.stack.append(_NULL)

    def _op_POP_TOP(self, f, ins):
        f.stack.pop()

    def _op_COPY(self, f, ins):
        f.stack.append(f.stack[-ins.arg])

    def _op_SWAP(self, f, ins):
        f.stack[-1], f.stack[-ins.arg] = f.stack[-ins.arg], f.stack[-1]

    # -- cells / closures -------------------------------------------------
    def _cell_slot(self, ins):
        # dis exposes the variable NAME; cells are stored by their
        # position in co_cellvars + co_freevars (parameter cells share
        # a fast-local slot in CPython, but reads/writes to them always
        # go through *_DEREF, so a separate cell array is equivalent)
        name = ins.argval
        cv = self.code.co_cellvars
        if name in cv:
            return cv.index(name)
        return len(cv) + self.code.co_freevars.index(name)

    def _op_MAKE_CELL(self, f, ins):
        idx = ins.arg
        cur = f.locals[idx] if idx < len(f.locals) else _UNBOUND
        cell = types.CellType() if cur is _UNBOUND \
            else types.CellType(cur)
        f.cells[self._cell_slot(ins)] = cell

    def _get_cell(self, f, ins):
        c = f.cells[self._cell_slot(ins)]
        if c is None:
            raise GraphBreak(f"uninitialized cell {ins.argval!r}")
        return c

    def _op_COPY_FREE_VARS(self, f, ins):
        ncv = len(self.code.co_cellvars)
        if len(self.closure) < ins.arg:
            raise GraphBreak("missing closure cells")
        for i in range(ins.arg):
            f.cells[ncv + i] = self.closure[i]

    def _op_LOAD_DEREF(self, f, ins):
        c = self._get_cell(f, ins)
        try:
            f.stack.append(c.cell_contents)
        except ValueError:
            raise GraphBreak(f"empty cell {ins.argval!r}")

    def _op_STORE_DEREF(self, f, ins):
        if self.state.fork_depth > 0 or self.state.strict:
            raise GraphBreak(
                "cell store under capture (closure cells outlive the "
                "call)")
        v = f.stack.pop()
        self.state.demote(v)
        self._get_cell(f, ins).cell_contents = v

    def _op_LOAD_CLOSURE(self, f, ins):
        f.stack.append(self._get_cell(f, ins))

    # -- attributes / subscripts ------------------------------------------
    def _op_LOAD_ATTR(self, f, ins):
        obj = f.stack.pop()
        try:
            v = getattr(obj, ins.argval)
        except AttributeError as e:
            raise GraphBreak(f"AttributeError: {e}")
        if ins.arg & 1:
            # method form: CPython pushes (unbound, self) or (NULL,
            # bound); pushing (NULL, bound) is call-equivalent
            f.stack.append(_NULL)
        f.stack.append(v)

    def _op_STORE_ATTR(self, f, ins):
        obj = f.stack.pop()
        v = f.stack.pop()
        self.state.guard_mutation(obj, "attribute store")
        if not self.state.is_fresh(obj):
            self.state.demote(v)  # v escapes into longer-lived state
        setattr(obj, ins.argval, v)

    def _op_BINARY_SUBSCR(self, f, ins):
        k = f.stack.pop()
        obj = f.stack.pop()
        f.stack.append(obj[k])

    def _op_STORE_SUBSCR(self, f, ins):
        k = f.stack.pop()
        obj = f.stack.pop()
        v = f.stack.pop()
        self.state.guard_mutation(obj, "subscript store")
        if not self.state.is_fresh(obj):
            self.state.demote(v)
        obj[k] = v

    def _op_DELETE_SUBSCR(self, f, ins):
        k = f.stack.pop()
        obj = f.stack.pop()
        self.state.guard_mutation(obj, "subscript delete")
        del obj[k]

    def _op_BINARY_SLICE(self, f, ins):
        stop = f.stack.pop()
        start = f.stack.pop()
        obj = f.stack.pop()
        f.stack.append(obj[slice(start, stop)])

    def _op_STORE_SLICE(self, f, ins):
        stop = f.stack.pop()
        start = f.stack.pop()
        obj = f.stack.pop()
        v = f.stack.pop()
        self.state.guard_mutation(obj, "slice store")
        if not self.state.is_fresh(obj):
            self.state.demote(v)
        obj[slice(start, stop)] = v

    # -- operators --------------------------------------------------------
    def _op_BINARY_OP(self, f, ins):
        b = f.stack.pop()
        a = f.stack.pop()
        try:
            fn = _BIN_OPS[ins.argrepr]
        except KeyError:
            raise GraphBreak(f"unknown BINARY_OP {ins.argrepr!r}")
        f.stack.append(fn(a, b))

    def _op_COMPARE_OP(self, f, ins):
        b = f.stack.pop()
        a = f.stack.pop()
        sym = ins.argval if isinstance(ins.argval, str) else ins.argrepr
        try:
            fn = _CMP_OPS[sym]
        except KeyError:
            raise GraphBreak(f"unknown COMPARE_OP {sym!r}")
        f.stack.append(fn(a, b))

    def _op_IS_OP(self, f, ins):
        b = f.stack.pop()
        a = f.stack.pop()
        f.stack.append((a is not b) if ins.arg else (a is b))

    def _op_CONTAINS_OP(self, f, ins):
        b = f.stack.pop()
        a = f.stack.pop()
        f.stack.append((a not in b) if ins.arg else (a in b))

    def _op_UNARY_NEGATIVE(self, f, ins):
        f.stack.append(-f.stack.pop())

    def _op_UNARY_INVERT(self, f, ins):
        f.stack.append(~f.stack.pop())

    def _op_UNARY_NOT(self, f, ins):
        v = f.stack.pop()
        b = _concrete_bool(v)
        if b is None:
            import jax.numpy as jnp
            from ..framework.tensor import Tensor
            f.stack.append(Tensor(jnp.logical_not(_as_array(v))))
        else:
            f.stack.append(not b)

    # -- containers -------------------------------------------------------
    def _popn(self, f, n):
        if n == 0:
            return []
        vs = f.stack[-n:]
        del f.stack[-n:]
        return vs

    def _op_BUILD_TUPLE(self, f, ins):
        v = tuple(self._popn(f, ins.arg))
        if any(self.state.is_fresh(x) for x in v):
            self.state.mark_fresh(v)  # aliases call-local objects
        f.stack.append(v)

    def _op_BUILD_LIST(self, f, ins):
        v = self._popn(f, ins.arg)
        self.state.mark_fresh(v)
        f.stack.append(v)

    def _op_BUILD_SET(self, f, ins):
        v = set(self._popn(f, ins.arg))
        self.state.mark_fresh(v)
        f.stack.append(v)

    def _op_BUILD_MAP(self, f, ins):
        vs = self._popn(f, 2 * ins.arg)
        v = {vs[i]: vs[i + 1] for i in range(0, len(vs), 2)}
        self.state.mark_fresh(v)
        f.stack.append(v)

    def _op_BUILD_CONST_KEY_MAP(self, f, ins):
        keys = f.stack.pop()
        vs = self._popn(f, ins.arg)
        v = dict(zip(keys, vs))
        self.state.mark_fresh(v)
        f.stack.append(v)

    def _op_BUILD_SLICE(self, f, ins):
        f.stack.append(slice(*self._popn(f, ins.arg)))

    def _op_BUILD_STRING(self, f, ins):
        f.stack.append("".join(self._popn(f, ins.arg)))

    def _op_FORMAT_VALUE(self, f, ins):
        have_spec = (ins.arg & 0x04) == 0x04
        spec = f.stack.pop() if have_spec else ""
        v = f.stack.pop()
        conv = ins.arg & 0x03
        if conv == 1:
            v = str(v)
        elif conv == 2:
            v = repr(v)
        elif conv == 3:
            v = ascii(v)
        f.stack.append(format(v, spec))

    def _op_LIST_EXTEND(self, f, ins):
        it = f.stack.pop()
        tgt = f.stack[-ins.arg]
        self.state.guard_mutation(tgt, "list extend")
        tgt.extend(it)

    def _op_LIST_APPEND(self, f, ins):
        v = f.stack.pop()
        tgt = f.stack[-ins.arg]
        self.state.guard_mutation(tgt, "list append")
        tgt.append(v)

    def _op_SET_ADD(self, f, ins):
        v = f.stack.pop()
        tgt = f.stack[-ins.arg]
        self.state.guard_mutation(tgt, "set add")
        tgt.add(v)

    def _op_SET_UPDATE(self, f, ins):
        it = f.stack.pop()
        tgt = f.stack[-ins.arg]
        self.state.guard_mutation(tgt, "set update")
        tgt.update(it)

    def _op_MAP_ADD(self, f, ins):
        v = f.stack.pop()
        k = f.stack.pop()
        tgt = f.stack[-ins.arg]
        self.state.guard_mutation(tgt, "dict add")
        tgt[k] = v

    def _op_DICT_UPDATE(self, f, ins):
        d = f.stack.pop()
        tgt = f.stack[-ins.arg]
        self.state.guard_mutation(tgt, "dict update")
        tgt.update(d)

    _op_DICT_MERGE = _op_DICT_UPDATE

    def _op_UNPACK_SEQUENCE(self, f, ins):
        vs = list(f.stack.pop())
        if len(vs) != ins.arg:
            raise GraphBreak("unpack length mismatch")
        f.stack.extend(reversed(vs))

    def _op_UNPACK_EX(self, f, ins):
        before = ins.arg & 0xFF
        after = ins.arg >> 8
        vs = list(f.stack.pop())
        if len(vs) < before + after:
            raise GraphBreak("unpack-ex length mismatch")
        split = len(vs) - after
        for v in reversed(vs[split:]):
            f.stack.append(v)
        f.stack.append(vs[before:split])
        for v in reversed(vs[:before]):
            f.stack.append(v)

    def _op_GET_LEN(self, f, ins):
        f.stack.append(len(f.stack[-1]))

    # -- jumps ------------------------------------------------------------
    def _op_JUMP_FORWARD(self, f, ins):
        self._jump(f, ins.argval)
        return _JUMPED

    def _op_JUMP_BACKWARD(self, f, ins):
        self._jump(f, ins.argval)
        return _JUMPED

    _op_JUMP_BACKWARD_NO_INTERRUPT = _op_JUMP_BACKWARD

    def _op_POP_JUMP_IF_FALSE(self, f, ins):
        return self._branch(f, ins, jump_when=False)

    def _op_POP_JUMP_IF_TRUE(self, f, ins):
        return self._branch(f, ins, jump_when=True)

    def _op_POP_JUMP_IF_NONE(self, f, ins):
        if f.stack.pop() is None:
            self._jump(f, ins.argval)
            return _JUMPED

    def _op_POP_JUMP_IF_NOT_NONE(self, f, ins):
        if f.stack.pop() is not None:
            self._jump(f, ins.argval)
            return _JUMPED

    # -- iteration --------------------------------------------------------
    def _op_GET_ITER(self, f, ins):
        src = f.stack.pop()
        if (self.state.fork_depth > 0 or self.state.strict) \
                and type(src).__module__ != "builtins" \
                and not isinstance(src, _SAFE_ITERABLES) \
                and not _is_tensorish(src):
            # iter() on a user object runs its __iter__ (and each loop
            # step its __next__) natively — unvetted code in both arms
            raise GraphBreak(
                f"iterating user object {type(src).__name__} under fork")
        it = iter(src)
        self.state.mark_fresh(it)
        f.stack.append(it)

    def _op_FOR_ITER(self, f, ins):
        it = f.stack[-1]
        # advancing an iterator created BEFORE the fork would double-
        # advance it (both arms run); loops wholly inside an arm made
        # their iterator post-fork via GET_ITER, which marks it fresh
        self.state.guard_mutation(it, "advancing a pre-fork iterator")
        try:
            f.stack.append(next(it))
        except StopIteration:
            f.stack.append(None)  # END_FOR pops iterator + this
            self._jump(f, ins.argval)
            return _JUMPED

    def _op_END_FOR(self, f, ins):
        f.stack.pop()
        f.stack.pop()

    # -- calls ------------------------------------------------------------
    def _op_KW_NAMES(self, f, ins):
        f.kwnames = ins.argval

    def _op_CALL(self, f, ins):
        argc = ins.arg
        kwnames = f.kwnames
        f.kwnames = ()
        args = self._popn(f, argc)
        b = f.stack.pop()
        a = f.stack.pop()
        if a is _NULL:
            func = b
        else:
            func = a
            args = [b] + args
        kwargs = {}
        if kwnames:
            nkw = len(kwnames)
            kwargs = dict(zip(kwnames, args[-nkw:]))
            args = args[:-nkw]
        f.stack.append(self._call(func, args, kwargs))

    def _op_CALL_FUNCTION_EX(self, f, ins):
        kwargs = f.stack.pop() if ins.arg & 1 else {}
        args = list(f.stack.pop())
        func = f.stack.pop()
        if f.stack and f.stack[-1] is _NULL:
            f.stack.pop()
        f.stack.append(self._call(func, args, dict(kwargs)))

    def _call(self, func, args, kwargs):
        st = self.state
        if st.fork_depth > 0 or st.strict:
            if self._vet_forked(func, args) == "interpret":
                return self._interpret(func, args, kwargs)
        elif self._may_retain_args(func):
            # an un-vetted native callee may retain its arguments —
            # they can no longer be treated as call-local
            for v in args:
                st.demote(v)
            for v in kwargs.values():
                st.demote(v)
        try:
            r = func(*args, **kwargs)
        except jax.errors.TracerBoolConversionError:
            # the callee branches on a tensor: interpret it too
            return self._interpret(func, args, kwargs)
        f0 = _unwrap_partials(func)
        if _safe_in(f0, _CTOR_TYPES) or f0 is sorted:
            if isinstance(r, _FRESH_TYPES):
                st.mark_fresh(r)  # constructor results are new objects
            elif isinstance(r, tuple) and \
                    any(st.is_fresh(x) for x in r):
                st.mark_fresh(r)
        return r

    def _may_retain_args(self, func) -> bool:
        """Could a native call alias its arguments into state that
        outlives this call? Known-pure callees cannot; a mutating
        container method retains args only inside its receiver, which
        is harmless when the receiver itself is call-local."""
        f0 = _unwrap_partials(func)
        if _safe_in(f0, _PURE_BUILTINS) or f0 is next or f0 is print:
            return False
        if isinstance(f0, type):
            return f0 not in _CTOR_TYPES
        self_obj = getattr(f0, "__self__", None)
        if self_obj is not None \
                and not isinstance(self_obj, types.ModuleType):
            if _is_tensorish(self_obj):
                return False
            name = getattr(f0, "__name__", "")
            if _is_mutating_method(name, self_obj):
                return not self.state.is_fresh(self_obj)
            if type(self_obj).__module__ == "builtins":
                return False
            return True
        mod = getattr(f0, "__module__", None)
        if mod is None and isinstance(self_obj, types.ModuleType):
            mod = self_obj.__name__
        if mod and _trusted_module(mod):
            return False
        return True

    def _interpret(self, func, args, kwargs):
        """Run a callee through the interpreter (shared state, so its
        side-effecting opcodes stay guarded while a fork is active)."""
        if self.call_depth >= _MAX_CALL_DEPTH:
            raise GraphBreak("interpreted callee too deep")
        import functools
        target = func
        while isinstance(target, functools.partial):
            args = list(target.args) + list(args)
            kwargs = {**target.keywords, **kwargs}
            target = target.func
        if isinstance(target, types.MethodType):
            args = [target.__self__] + list(args)
            target = target.__func__
        if isinstance(target, OpcodeFunction):
            target = target.fn  # re-enter with OUR shared state
        if not isinstance(target, types.FunctionType):
            raise GraphBreak(f"cannot interpret callee {func!r}")
        # A fork INSIDE the callee copies only the callee frame's view
        # of these objects; our continuation would keep reading the
        # originals and miss the taken arm's mutations — so they stop
        # being call-local here (mutation under a fork then GraphBreaks
        # instead of silently diverging).
        for v in args:
            self.state.demote(v)
        for v in kwargs.values():
            self.state.demote(v)
        sub = OpcodeFunction(target, state=self.state,
                             call_depth=self.call_depth + 1)
        return sub(*args, **kwargs)

    def _vet_forked(self, func, args) -> str:
        """Decide how to perform a call while a tensor-if fork is
        active: ``"native"`` (known side-effect-free, or mutation target
        verified fresh), ``"interpret"`` (Python code — run it through
        the interpreter so its effects stay guarded), or GraphBreak.
        Both arms of the fork execute under trace, so an unvetted native
        call could leak the untaken arm's side effects (ADVICE r3)."""
        st = self.state
        f0 = _unwrap_partials(func)
        if isinstance(f0, OpcodeFunction):
            return "interpret"
        if isinstance(f0, types.MethodType):
            inner = f0.__func__
            self_obj = f0.__self__
            if _is_tensorish(self_obj):
                name = getattr(inner, "__name__", "")
                if name.endswith("_") and not name.endswith("__"):
                    raise GraphBreak(
                        f"in-place tensor method {name!r} under fork")
                return "native"
            # Python-level methods always go through the interpreter —
            # a native call could mutate globals/attrs unvetted even
            # when the receiver itself is arm-local
            if isinstance(inner, types.FunctionType):
                return "interpret"
            if st.is_fresh_current(self_obj):
                return "native"
            raise GraphBreak(
                f"bound method {f0!r} on a pre-fork object under fork")
        if isinstance(f0, type):
            # range/enumerate/zip/reversed are TYPES in CPython — vet
            # them (and container ctors) for iteration-protocol safety
            if f0 in (list, tuple, set, frozenset, dict, range,
                      enumerate, zip, reversed):
                if not all(_fork_iter_safe(a) for a in args):
                    raise GraphBreak(
                        "ctor iterating a user object under capture")
                return "native"
            if f0 in _CTOR_TYPES or _trusted_module(f0.__module__):
                return "native"
            raise GraphBreak(f"constructor {f0!r} under capture")
        if _safe_in(f0, _FORBIDDEN_BUILTINS):
            raise GraphBreak(
                f"side-effecting builtin {f0!r} under fork")
        if _safe_in(f0, _ITERATING_BUILTINS) and \
                not all(_fork_iter_safe(a) for a in args):
            raise GraphBreak(
                "builtin iterating a user object under fork")
        if _safe_in(f0, _PURE_BUILTINS):
            return "native"
        if f0 is next:
            if args:
                st.guard_mutation(args[0], "next() advancing iterator")
            return "native"
        self_obj = getattr(f0, "__self__", None)
        if self_obj is not None \
                and not isinstance(self_obj, types.ModuleType):
            # bound C-level method (list.append, ndarray.sum, ...)
            if _is_tensorish(self_obj):
                return "native"
            name = getattr(f0, "__name__", "")
            if _is_mutating_method(name, self_obj):
                st.guard_mutation(self_obj, f"method .{name}()")
                return "native"
            tm = type(self_obj).__module__
            if tm == "builtins" or _trusted_module(tm):
                return "native"
            raise GraphBreak(
                f"C method {f0!r} on unknown object under fork")
        mod = getattr(f0, "__module__", None)
        if mod is None and isinstance(self_obj, types.ModuleType):
            mod = self_obj.__name__
        if mod and _trusted_module(mod):
            name = getattr(f0, "__name__", "")
            if name.endswith("_") and not name.endswith("__"):
                raise GraphBreak(
                    f"in-place API {name!r} under fork")
            return "native"
        if isinstance(f0, types.FunctionType):
            return "interpret"
        raise GraphBreak(
            f"potentially side-effecting callee {f0!r} under fork")

    def _op_MAKE_FUNCTION(self, f, ins):
        code = f.stack.pop()
        closure = f.stack.pop() if ins.arg & 0x08 else None
        if ins.arg & 0x04:
            f.stack.pop()  # annotations
        kwdefaults = f.stack.pop() if ins.arg & 0x02 else None
        defaults = f.stack.pop() if ins.arg & 0x01 else None
        fn = types.FunctionType(code, self.globals, code.co_name,
                                defaults, closure)
        if kwdefaults:
            fn.__kwdefaults__ = dict(kwdefaults)
        f.stack.append(fn)

    def _op_RETURN_GENERATOR(self, f, ins):
        raise GraphBreak("generator")

    # -- imports (idempotent; run natively) -------------------------------
    def _op_IMPORT_NAME(self, f, ins):
        fromlist = f.stack.pop()
        level = f.stack.pop()
        f.stack.append(__import__(ins.argval, self.globals, None,
                                  fromlist, level))

    def _op_IMPORT_FROM(self, f, ins):
        try:
            f.stack.append(getattr(f.stack[-1], ins.argval))
        except AttributeError:
            raise GraphBreak(f"import-from failed: {ins.argval}")

    # -- with-blocks (no-exception path) ----------------------------------
    def _op_BEFORE_WITH(self, f, ins):
        cm = f.stack.pop()
        f.stack.append(cm.__exit__)
        f.stack.append(cm.__enter__())

    # -- exceptions: only reachable when something actually raised --------
    def _op_PUSH_EXC_INFO(self, f, ins):
        raise GraphBreak("exception handling under trace")

    _op_CHECK_EXC_MATCH = _op_PUSH_EXC_INFO
    _op_RERAISE = _op_PUSH_EXC_INFO
    _op_WITH_EXCEPT_START = _op_PUSH_EXC_INFO
    _op_CLEANUP_THROW = _op_PUSH_EXC_INFO

    def _op_RAISE_VARARGS(self, f, ins):
        if ins.arg == 1:
            raise f.stack.pop()
        raise GraphBreak("re-raise forms")


class OpcodeFunction:
    """Callable wrapper: interpret ``fn``'s bytecode on every call.

    The values flowing through are whatever the caller passes — under
    ``jax.jit`` tracing they are tracers, which is what makes tensor-if
    conversion produce a compiled ``lax.cond``.
    """

    def __init__(self, fn: Callable, state: Optional[_State] = None,
                 call_depth=0, strict=False):
        self._strict = strict
        if isinstance(fn, types.MethodType):
            self._self = fn.__self__
            fn = fn.__func__
        else:
            self._self = None
        if not isinstance(fn, types.FunctionType):
            raise GraphBreak(f"not a Python function: {fn!r}")
        self.fn = fn
        self.state = state
        self.call_depth = call_depth

    def __call__(self, *args, **kwargs):
        fn = self.fn
        if self._self is not None:
            args = (self._self,) + args
        try:
            ba = inspect.signature(fn).bind(*args, **kwargs)
        except TypeError as e:
            raise GraphBreak(f"bad call signature: {e}")
        ba.apply_defaults()
        state = self.state if self.state is not None \
            else _State(strict=self._strict)
        ex = OpcodeExecutor(fn.__code__, fn.__globals__, fn.__closure__,
                            state, self.call_depth)
        return ex.run(dict(ba.arguments))


def interpretable(fn: Callable) -> bool:
    """Can OpcodeFunction even attempt this function?"""
    target = fn.__func__ if isinstance(fn, types.MethodType) else fn
    return isinstance(target, types.FunctionType) \
        and not (target.__code__.co_flags & _GEN_FLAGS)
