"""jit.save / jit.load — deployable model export.

Reference: python/paddle/jit/api.py save/load producing .pdmodel/.pdiparams
consumed by AnalysisPredictor. TPU-native: export the traced function as
StableHLO via jax.export (the serving IR for XLA), with params embedded or
saved alongside; load returns a callable that executes via XLA.
"""
from __future__ import annotations

import json
import os
import pickle
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.io import load as fw_load
from ..framework.io import save as fw_save
from ..framework.tensor import Tensor
from ..nn.layer_base import Layer
from .static_function import InputSpec, StaticFunction, _unwrap_tree, \
    _wrap_tree

__all__ = ["save", "load", "TranslatedLayer"]


def _spec_to_aval(spec: InputSpec):
    from ..framework.dtype import to_dtype
    shape = tuple(1 if s is None or s == -1 else int(s)
                  for s in spec.shape)
    return jax.ShapeDtypeStruct(shape, to_dtype(spec.dtype).np_dtype)


def save(layer, path: str, input_spec: Optional[Sequence] = None, **configs):
    """Export ``layer`` (Layer or StaticFunction) to:
    - ``{path}.stablehlo.mlir``: serialized StableHLO of eval-mode forward
    - ``{path}.pdiparams``: parameters + buffers (framework.io format)
    - ``{path}.pdmeta``: input specs + structure metadata
    """
    static = layer if isinstance(layer, StaticFunction) else None
    net: Layer = static.layer if static is not None else layer
    if not isinstance(net, Layer):
        raise TypeError("jit.save expects a Layer or to_static(Layer)")
    if input_spec is None:
        raise ValueError("input_spec is required for jit.save")
    specs = [s if isinstance(s, InputSpec) else
             InputSpec(s.shape, s.dtype.name if hasattr(s.dtype, "name")
                       else str(s.dtype)) for s in input_spec]

    params, buffers = net.raw_state()
    net.eval()

    # AST-convert the forward so tensor-dependent control flow exports
    # as lax.cond/while_loop instead of failing under tracing (same pass
    # StaticFunction._build applies)
    from .dy2static import convert_to_static
    fwd = convert_to_static(type(net).forward)

    def infer_fn(params_, buffers_, *inputs):
        wrapped = [Tensor(a) for a in inputs]
        with net.bind_state(params_, buffers_):
            out = fwd(net, *wrapped)
        return _unwrap_tree(out)

    avals = [_spec_to_aval(s) for s in specs]
    p_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
               for k, v in params.items()}
    b_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
               for k, v in buffers.items()}
    exported = jax.export.export(jax.jit(infer_fn))(p_avals, b_avals, *avals)
    blob = exported.serialize()

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".stablehlo.mlir", "wb") as f:
        f.write(blob)
    fw_save({"params": {k: Tensor(v) for k, v in params.items()},
             "buffers": {k: Tensor(v) for k, v in buffers.items()}},
            path + ".pdiparams")
    with open(path + ".pdmeta", "w") as f:
        json.dump({"input_specs": [
            {"shape": list(s.shape), "dtype": s.dtype
             if isinstance(s.dtype, str) else s.dtype.name,
             "name": getattr(s, "name", None)}
            for s in specs]}, f)


class TranslatedLayer:
    """Loaded deployable model (reference: fluid/jit/layer.cc C++ Layer +
    python TranslatedLayer). Callable; runs the deserialized StableHLO."""

    def __init__(self, exported, params, buffers):
        self._exported = exported
        self._params = params
        self._buffers = buffers

    def __call__(self, *args):
        arrs = tuple(a._data if isinstance(a, Tensor) else jnp.asarray(a)
                     for a in args)
        out = self._exported.call(self._params, self._buffers, *arrs)
        return _wrap_tree(out)

    forward = __call__

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("loaded inference program cannot be trained; "
                           "load parameters with paddle_tpu.load instead")


def load_artifacts(prefix: str):
    """Deserialize a jit.save'd model: (exported, params, buffers).
    Shared by jit.load and inference.Predictor."""
    with open(prefix + ".stablehlo.mlir", "rb") as f:
        exported = jax.export.deserialize(f.read())
    state = fw_load(prefix + ".pdiparams")
    params = {k: v._data for k, v in state["params"].items()}
    buffers = {k: v._data for k, v in state["buffers"].items()}
    return exported, params, buffers


def load(path: str, **configs) -> TranslatedLayer:
    return TranslatedLayer(*load_artifacts(path))
