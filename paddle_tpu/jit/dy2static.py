"""Dy2Static: AST conversion of Python control flow to compilable ops.

Reference: ``python/paddle/jit/dy2static/`` — ``program_translator.py``
StaticFunction/ConcreteProgram and the AST transformers under
``transformers/`` that rewrite ``if``/``while``/``for`` into
``cond``/``while_loop`` ops (plus the early-return and loop-variable
analyses).

TPU-native rethink: under jax tracing, a tensor-dependent ``if pred:``
raises (a tracer has no truth value) — exactly the reference's
dygraph-to-static problem. The converter rewrites control flow into
calls to the runtime helpers below, which dispatch on the *runtime*
value of the predicate:

- concrete value (eager, or Python scalar): plain Python control flow —
  identical to reference dygraph semantics;
- traced value (inside ``jit.to_static``/``jax.jit``): ``lax.cond`` /
  ``lax.while_loop`` — branch/body closures are re-expressed as pure
  functions of the variables they assign, with initial values captured
  by deferred loaders (unbound names become ``UndefinedVar``, the
  reference's placeholder for maybe-unassigned branch variables).

Conversion is best-effort with graph-break semantics (SURVEY.md §7 hard
part 4): if a function can't be converted (no source, exotic syntax),
the original function is used unchanged.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any, Callable, Dict

import jax

__all__ = ["convert_to_static", "convert_ifelse", "convert_while_loop",
           "convert_for_range", "convert_logical_and",
           "convert_logical_or", "convert_logical_not", "UndefinedVar"]

_CONVERTED: Dict[Callable, Callable] = {}


class _Unchanged(Exception):
    """Internal: AST pass found no control flow to convert."""


class UndefinedVar:
    """Placeholder for a branch/loop variable with no value yet
    (reference dy2static UndefinedVar). Any USE of it raises, preserving
    Python's unbound-variable error semantics; only identity checks and
    repr are allowed."""
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<UndefinedVar>"

    def _raise(self, *a, **k):
        raise NameError(
            "dy2static: variable was not assigned on the taken branch "
            "(UndefinedVar used)")

    __bool__ = __call__ = __iter__ = __len__ = _raise
    __add__ = __radd__ = __sub__ = __mul__ = __eq__ = __lt__ = _raise
    __getitem__ = __getattr__ = _raise

    def __hash__(self):
        return object.__hash__(self)


UNDEFINED = UndefinedVar()


# ---------------------------------------------------------------------------
# runtime helpers
# ---------------------------------------------------------------------------

def _is_traced(x) -> bool:
    from ..framework.tensor import Tensor
    if isinstance(x, Tensor):
        x = x._data
    return isinstance(x, jax.core.Tracer)


def _data(x):
    from ..framework.tensor import Tensor
    return x._data if isinstance(x, Tensor) else x


def _load_inits(loaders):
    out = []
    for ld in loaders:
        try:
            out.append(ld())
        except NameError:
            out.append(UNDEFINED)
    return tuple(out)


def _unwrap(tree):
    from ..framework.tensor import Tensor
    return jax.tree.map(
        lambda t: t._data if isinstance(t, Tensor) else t, tree,
        is_leaf=lambda t: isinstance(t, Tensor))


def _rewrap(data_tree, template_tree):
    from ..framework.tensor import Tensor
    flat_d = jax.tree.leaves(data_tree)
    flat_t, treedef = jax.tree.flatten(
        template_tree, is_leaf=lambda t: isinstance(t, Tensor))
    out = [Tensor(d, stop_gradient=True) if isinstance(t, Tensor) else d
           for d, t in zip(flat_d, flat_t)]
    return jax.tree.unflatten(treedef, out)


def _check_no_undefined(tree, what):
    if any(isinstance(v, UndefinedVar) for v in jax.tree.leaves(
            tree, is_leaf=lambda v: isinstance(v, UndefinedVar))):
        raise ValueError(
            f"dy2static: {what} must be initialized before a "
            f"tensor-dependent (traced) control-flow statement")


def convert_ifelse(pred, true_fn, false_fn, loaders=(),
                   returns_value=False):
    """`if pred:` with branches lifted to functions of their assigned
    variables. Concrete pred → Python semantics; traced pred →
    lax.cond."""
    init = _load_inits(loaders)
    if not _is_traced(pred):
        return true_fn(*init) if bool(_data(pred)) else false_fn(*init)

    template = {}

    def wrap(fn):
        def inner(_):
            out = fn(*init)
            # a branch may receive UndefinedVar initials (vars assigned
            # in both branches); it must not RETURN one — that means one
            # branch left a variable unassigned that the other assigns
            _check_no_undefined(out, "every variable assigned in a "
                                "traced if/else branch")
            template.setdefault("t", out)
            return _unwrap(out)
        return inner

    out = jax.lax.cond(_data(pred), wrap(true_fn), wrap(false_fn), None)
    return _rewrap(out, template["t"])


def convert_while_loop(cond_fn, body_fn, loaders=()):
    """`while cond: body` — all assigned names become loop carries. The
    traced path is taken when any loop variable is a tracer; a traced
    condition over non-carried values would have raised in the original
    code too, so no extra condition probe is made (side-effecting
    conditions run exactly as often as in the source).

    Graph-break recovery (the SOT fallback idea): if staging the body
    fails because it needs a concrete value of a carried python scalar
    (e.g. ``float(i)`` on the loop counter), fall back to the eager
    python loop — the body unrolls into the surrounding trace instead
    of erroring out. Caveat: the failed staging attempt traced the body
    once, so python-level side effects NOT expressed through loop vars
    (e.g. list.append on a closed-over list) would run twice; lifted
    bodies produced by the AST pass only assign loop vars, keeping the
    retry safe for converted code."""
    loop_vars = _load_inits(loaders)
    traced = any(
        _is_traced(v) for v in jax.tree.leaves(
            _unwrap(loop_vars),
            is_leaf=lambda v: isinstance(v, UndefinedVar)))
    if traced:
        _check_no_undefined(loop_vars, "loop variables")
        template = tuple(loop_vars)

        def cond_w(carry):
            return _data(cond_fn(*_rewrap(carry, template)))

        def body_w(carry):
            return _unwrap(tuple(body_fn(*_rewrap(carry, template))))

        try:
            out = jax.lax.while_loop(cond_w, body_w, _unwrap(template))
            return _rewrap(out, template)
        except (jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerIntegerConversionError):
            pass  # body needs concrete python values: unroll below
    while bool(_data(cond_fn(*loop_vars))):
        loop_vars = tuple(body_fn(*loop_vars))
    return loop_vars


def convert_for_range(start, stop, step, body_fn, loaders=()):
    """`for i in range(...)` — body_fn(i, *loop_vars) -> loop_vars."""
    loop_vars = _load_inits(loaders)
    if not any(_is_traced(v) for v in (start, stop, step)):
        for i in range(int(_data(start)), int(_data(stop)),
                       int(_data(step))):
            loop_vars = tuple(body_fn(i, *loop_vars))
        return loop_vars

    _check_no_undefined(loop_vars, "loop variables")
    import jax.numpy as jnp
    step_d = _data(step)

    def cond_fn(i, *vs):
        from ..framework.tensor import Tensor
        return Tensor(jnp.where(step_d > 0,
                                _data(i) < _data(stop),
                                _data(i) > _data(stop)),
                      stop_gradient=True)

    def body_w(i, *vs):
        out = body_fn(i, *vs)
        return (i + step, *out)

    out = convert_while_loop(cond_fn, body_w,
                             tuple([lambda s=start: s]
                                   + [lambda v=v: v for v in loop_vars]))
    return tuple(out[1:])


def convert_logical_and(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if not _is_traced(lhs):
        return lhs and rhs_fn()
    import jax.numpy as jnp
    from ..framework.tensor import Tensor
    return Tensor(jnp.logical_and(_data(lhs), _data(rhs_fn())),
                  stop_gradient=True)


def convert_logical_or(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if not _is_traced(lhs):
        return lhs or rhs_fn()
    import jax.numpy as jnp
    from ..framework.tensor import Tensor
    return Tensor(jnp.logical_or(_data(lhs), _data(rhs_fn())),
                  stop_gradient=True)


def convert_logical_not(x):
    if not _is_traced(x):
        return not bool(_data(x))
    import jax.numpy as jnp
    from ..framework.tensor import Tensor
    return Tensor(jnp.logical_not(_data(x)), stop_gradient=True)


# ---------------------------------------------------------------------------
# AST analysis
# ---------------------------------------------------------------------------

class _NameCollector(ast.NodeVisitor):
    def __init__(self):
        self.stored = set()
        self.loaded = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Store):
            self.stored.add(node.id)
        else:
            self.loaded.add(node.id)

    def visit_FunctionDef(self, node):
        pass  # don't descend into nested defs

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def _visit_comp(self, node):
        """Comprehension targets live in their own scope (py3) — they
        are NOT assignments of the enclosing block."""
        targets = _NameCollector()
        sub = _NameCollector()
        for gen in node.generators:
            targets.visit(gen.target)
            sub.visit(gen.iter)
            for cond in gen.ifs:
                sub.visit(cond)
        for attr in ("elt", "key", "value"):
            if hasattr(node, attr):
                sub.visit(getattr(node, attr))
        self.stored |= (sub.stored - targets.stored)
        self.loaded |= (sub.loaded - targets.stored)

    visit_ListComp = _visit_comp
    visit_SetComp = _visit_comp
    visit_DictComp = _visit_comp
    visit_GeneratorExp = _visit_comp


def _names(nodes, kind):
    c = _NameCollector()
    for n in nodes:
        c.visit(n)
    return c.stored if kind == "store" else c.loaded


_DISALLOWED = (ast.Return, ast.Break, ast.Continue, ast.Yield,
               ast.YieldFrom, ast.Global, ast.Nonlocal, ast.Import,
               ast.ImportFrom, ast.FunctionDef, ast.AsyncFunctionDef,
               ast.ClassDef)


def _has_disallowed(nodes, allow_trailing_return=False):
    """Bodies we can't lift into a closure: control-transfer statements
    (a trailing return is allowed in return-style branches),
    name-scope-changing statements (global/nonlocal/import/def), and
    attribute/subscript stores (side effects a lax.cond would apply
    unconditionally while tracing both branches). Closures GENERATED by
    this converter (``__dy2st_*``) are self-contained and allowed —
    they appear when an inner if/loop has already been lowered."""
    seq = list(nodes)
    if allow_trailing_return and seq and isinstance(seq[-1], ast.Return):
        seq = seq[:-1]

    def scan(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name.startswith("__dy2st_"):
            return False
        if isinstance(node, _DISALLOWED):
            return True
        if isinstance(node, (ast.Attribute, ast.Subscript)) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
        return any(scan(c) for c in ast.iter_child_nodes(node))

    return any(scan(n) for n in seq)


def _ends_with_return(body):
    return bool(body) and isinstance(body[-1], ast.Return)


def _dy2st_attr(name):
    return ast.Attribute(value=ast.Name(id="__dy2st", ctx=ast.Load()),
                         attr=name, ctx=ast.Load())


def _empty_args(n_args=0, names=None):
    args = [ast.arg(arg=a) for a in (names or [])]
    return ast.arguments(posonlyargs=[], args=args, vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _loaders_tuple(names):
    """(lambda: x, lambda: y, ...) — deferred loads so unbound names
    surface as UndefinedVar at runtime, not NameError at the call."""
    return ast.Tuple(
        elts=[ast.Lambda(args=_empty_args(), body=ast.Name(
            id=n, ctx=ast.Load())) for n in names],
        ctx=ast.Load())


def _name_tuple(names, ctx):
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names],
                     ctx=ctx())


class _EarlyReturnMerger(ast.NodeTransformer):
    """stmts [If(test, body..return, orelse=[]), rest...] →
    If(test, body..return, orelse=rest) — the reference's early-return
    normalization, making both branches return-style convertible."""

    def _merge(self, stmts):
        out = []
        for i, st in enumerate(stmts):
            st = self.visit(st)
            if (isinstance(st, ast.If) and _ends_with_return(st.body)
                    and not st.orelse and i + 1 < len(stmts)):
                rest = self._merge(stmts[i + 1:])
                st.orelse = rest
                out.append(st)
                return out
            out.append(st)
        return out

    def visit_FunctionDef(self, node):
        node.body = self._merge(node.body)
        return node

    visit_AsyncFunctionDef = visit_FunctionDef


def _has_break_continue(stmts):
    """True if a break/continue binds to THIS loop level (don't descend
    into nested loops or function defs, whose break/continue are theirs)."""
    stop = (ast.While, ast.For, ast.FunctionDef, ast.AsyncFunctionDef,
            ast.Lambda)

    def scan(nodes):
        for n in nodes:
            if isinstance(n, (ast.Break, ast.Continue)):
                return True
            if isinstance(n, stop):
                continue
            if scan(list(ast.iter_child_nodes(n))):
                return True
        return False

    return scan(stmts)


def _assign_flag(name, value):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=ast.Constant(value))


class _BreakContinueNormalizer(ast.NodeTransformer):
    """Rewrite break/continue into boolean flag variables (the
    reference's break_continue_transformer.py): a `break` becomes
    `__dy2st_brk_N = True`, `continue` becomes `__dy2st_cont_N = True`,
    statements after a potential flag-set are guarded by
    `if not (brk or cont):`, and the loop condition gains
    `not brk and ...`. The flags are ordinary assigned names, so the
    later _ControlFlowTransformer turns the guards into lax.cond and
    the loop into lax.while_loop — break/continue on tensor predicates
    become device control flow instead of graph breaks."""

    def __init__(self):
        self.counter = 0

    def _uid(self):
        self.counter += 1
        return self.counter

    def _rewrite_stmt(self, st, brk, cont):
        if isinstance(st, ast.Break):
            return [_assign_flag(brk, True)]
        if isinstance(st, ast.Continue):
            return [_assign_flag(cont, True)]
        if isinstance(st, ast.If):
            st = ast.If(test=st.test,
                        body=self._guard(st.body, brk, cont),
                        orelse=self._guard(st.orelse, brk, cont))
        return [st]

    def _guard(self, stmts, brk, cont):
        out = []
        for i, st in enumerate(stmts):
            may_flag = _has_break_continue([st])
            out.extend(self._rewrite_stmt(st, brk, cont))
            rest = stmts[i + 1:]
            if may_flag and rest:
                test = ast.UnaryOp(op=ast.Not(), operand=ast.BoolOp(
                    op=ast.Or(),
                    values=[ast.Name(id=brk, ctx=ast.Load()),
                            ast.Name(id=cont, ctx=ast.Load())]))
                out.append(ast.If(test=test,
                                  body=self._guard(rest, brk, cont),
                                  orelse=[]))
                return out
        return out

    def visit_While(self, node):
        self.generic_visit(node)  # innermost loops first
        if not _has_break_continue(node.body) or node.orelse:
            return node
        uid = self._uid()
        brk, cont = f"__dy2st_brk_{uid}", f"__dy2st_cont_{uid}"
        body = [_assign_flag(cont, False)] + \
            self._guard(node.body, brk, cont)
        test = ast.BoolOp(op=ast.And(), values=[
            ast.UnaryOp(op=ast.Not(),
                        operand=ast.Name(id=brk, ctx=ast.Load())),
            node.test])
        # cont is (re)set inside the body but is a carried loop var of
        # the eventual lax.while_loop -> must exist before loop entry
        return [_assign_flag(brk, False), _assign_flag(cont, False),
                ast.While(test=test, body=body, orelse=[])]

    def visit_For(self, node):
        self.generic_visit(node)
        if (not _has_break_continue(node.body) or node.orelse
                or not isinstance(node.target, ast.Name)
                or not (isinstance(node.iter, ast.Call)
                        and isinstance(node.iter.func, ast.Name)
                        and node.iter.func.id == "range")
                or node.iter.keywords):
            return node
        rargs = node.iter.args
        if len(rargs) == 1:
            start, stop, step = ast.Constant(0), rargs[0], ast.Constant(1)
        elif len(rargs) == 2:
            start, stop, step = rargs[0], rargs[1], ast.Constant(1)
        elif len(rargs) == 3:
            start, stop, step = rargs
        else:
            return node
        if not (isinstance(step, ast.Constant) and isinstance(
                step.value, int) and step.value > 0):
            return node  # only forward constant-step ranges
        # rewrite to a while so the break flag can live in the
        # condition. The internal counter advances at the TOP of the
        # body (before any continue-guarded region), so `continue`
        # cannot skip the increment. start/stop are captured ONCE into
        # temps (range() evaluates its arguments once; re-evaluating a
        # side-effecting/expensive stop per iteration would diverge).
        uid = self._uid()
        ivar = node.target.id
        cnt = f"__dy2st_iter_{uid}"
        stop_v = f"__dy2st_stop_{uid}"
        header = [
            ast.Assign(targets=[ast.Name(id=ivar, ctx=ast.Store())],
                       value=ast.Name(id=cnt, ctx=ast.Load())),
            ast.Assign(targets=[ast.Name(id=cnt, ctx=ast.Store())],
                       value=ast.BinOp(
                           left=ast.Name(id=cnt, ctx=ast.Load()),
                           op=ast.Add(), right=step)),
        ]
        loop = ast.While(
            test=ast.Compare(left=ast.Name(id=cnt, ctx=ast.Load()),
                             ops=[ast.Lt()],
                             comparators=[ast.Name(id=stop_v,
                                                   ctx=ast.Load())]),
            body=header + list(node.body), orelse=[])
        init = [
            ast.Assign(targets=[ast.Name(id=cnt, ctx=ast.Store())],
                       value=start),
            ast.Assign(targets=[ast.Name(id=stop_v, ctx=ast.Store())],
                       value=stop),
            # ivar is a carried var of the lowered while_loop:
            # initialize it (Python leaves it unbound when the range is
            # empty — acceptable divergence for the staged path)
            ast.Assign(targets=[ast.Name(id=ivar, ctx=ast.Store())],
                       value=ast.Name(id=cnt, ctx=ast.Load())),
        ]
        return init + self.visit_While(loop)


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0
        self.changed = False

    def _uid(self):
        self.counter += 1
        return self.counter

    # -- if ---------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        body_ret = _ends_with_return(node.body)
        orelse_ret = _ends_with_return(node.orelse)
        if body_ret and orelse_ret:
            if _has_disallowed(node.body, True) or \
                    _has_disallowed(node.orelse, True):
                return node
            return self._convert_if(node, returns_value=True)
        if _has_disallowed(node.body) or _has_disallowed(node.orelse):
            return node
        return self._convert_if(node, returns_value=False)

    def _convert_if(self, node, returns_value):
        self.changed = True
        assigned = sorted(_names(node.body, "store")
                          | _names(node.orelse, "store"))
        uid = self._uid()
        tname, fname = f"__dy2st_true_{uid}", f"__dy2st_false_{uid}"
        if returns_value:
            tbody = list(node.body)
            fbody = list(node.orelse)
        else:
            ret = ast.Return(value=_name_tuple(assigned, ast.Load))
            tbody = list(node.body) + [ret]
            fbody = (list(node.orelse) if node.orelse else []) + [ret]
        true_def = ast.FunctionDef(name=tname,
                                   args=_empty_args(names=assigned),
                                   body=tbody, decorator_list=[])
        false_def = ast.FunctionDef(name=fname,
                                    args=_empty_args(names=assigned),
                                    body=fbody, decorator_list=[])
        call = ast.Call(
            func=_dy2st_attr("convert_ifelse"),
            args=[node.test,
                  ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  _loaders_tuple(assigned),
                  ast.Constant(returns_value)],
            keywords=[])
        if returns_value:
            stmt = ast.Return(value=call)
        elif assigned:
            stmt = ast.Assign(targets=[_name_tuple(assigned, ast.Store)],
                              value=call)
        else:
            stmt = ast.Expr(value=call)
        return [true_def, false_def, stmt]

    # -- while ------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if _has_disallowed(node.body) or node.orelse:
            return node
        if _names([node.test], "store"):
            return node  # walrus in the condition: leave as Python
        loop_vars = sorted(_names(node.body, "store"))
        if not loop_vars:
            return node
        uid = self._uid()
        cname, bname = f"__dy2st_cond_{uid}", f"__dy2st_body_{uid}"
        cond_def = ast.FunctionDef(
            name=cname, args=_empty_args(names=loop_vars),
            body=[ast.Return(value=node.test)], decorator_list=[])
        ret = ast.Return(value=_name_tuple(loop_vars, ast.Load))
        body_def = ast.FunctionDef(
            name=bname, args=_empty_args(names=loop_vars),
            body=list(node.body) + [ret], decorator_list=[])
        call = ast.Call(
            func=_dy2st_attr("convert_while_loop"),
            args=[ast.Name(id=cname, ctx=ast.Load()),
                  ast.Name(id=bname, ctx=ast.Load()),
                  _loaders_tuple(loop_vars)],
            keywords=[])
        assign = ast.Assign(targets=[_name_tuple(loop_vars, ast.Store)],
                            value=call)
        self.changed = True
        return [cond_def, body_def, assign]

    # -- for i in range(...) ----------------------------------------------
    def visit_For(self, node):
        self.generic_visit(node)
        if (_has_disallowed(node.body) or node.orelse
                or not isinstance(node.target, ast.Name)
                or not (isinstance(node.iter, ast.Call)
                        and isinstance(node.iter.func, ast.Name)
                        and node.iter.func.id == "range")
                or node.iter.keywords):
            return node
        rargs = node.iter.args
        if len(rargs) == 1:
            start, stop, step = ast.Constant(0), rargs[0], ast.Constant(1)
        elif len(rargs) == 2:
            start, stop, step = rargs[0], rargs[1], ast.Constant(1)
        elif len(rargs) == 3:
            start, stop, step = rargs
        else:
            return node
        ivar = node.target.id
        loop_vars = sorted(_names(node.body, "store") - {ivar})
        uid = self._uid()
        bname = f"__dy2st_forbody_{uid}"
        ret = ast.Return(value=_name_tuple(loop_vars, ast.Load))
        body_def = ast.FunctionDef(
            name=bname, args=_empty_args(names=[ivar] + loop_vars),
            body=list(node.body) + [ret], decorator_list=[])
        call = ast.Call(
            func=_dy2st_attr("convert_for_range"),
            args=[start, stop, step,
                  ast.Name(id=bname, ctx=ast.Load()),
                  _loaders_tuple(loop_vars)],
            keywords=[])
        if loop_vars:
            stmt = ast.Assign(targets=[_name_tuple(loop_vars, ast.Store)],
                              value=call)
        else:
            stmt = ast.Expr(value=call)
        self.changed = True
        return [body_def, stmt]

    # -- boolean operators -------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        self.changed = True
        helper = ("convert_logical_and"
                  if isinstance(node.op, ast.And)
                  else "convert_logical_or")
        expr = node.values[-1]
        for val in reversed(node.values[:-1]):
            expr = ast.Call(
                func=_dy2st_attr(helper),
                args=[ast.Lambda(args=_empty_args(), body=val),
                      ast.Lambda(args=_empty_args(), body=expr)],
                keywords=[])
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            self.changed = True
            return ast.Call(func=_dy2st_attr("convert_logical_not"),
                            args=[node.operand], keywords=[])
        return node


def convert_to_static(fn: Callable) -> Callable:
    """AST-convert a function's control flow; returns the original on any
    failure (graph-break fallback). Results are cached per function."""
    if fn in _CONVERTED:
        return _CONVERTED[fn]
    try:
        # re-exec'ing at module scope loses the __class__ cell (no-arg
        # super()) and class-body name mangling — bail for such functions
        if "__class__" in fn.__code__.co_freevars:
            raise ValueError("uses zero-arg super()/__class__")
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
        fdef = tree.body[0]
        if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
            raise ValueError("not a function definition")
        fdef.decorator_list = []  # avoid re-applying @to_static etc.
        tree = _EarlyReturnMerger().visit(tree)
        tree = _BreakContinueNormalizer().visit(tree)
        transformer = _ControlFlowTransformer()
        new_tree = transformer.visit(tree)
        if not transformer.changed:
            # nothing to convert: keep the original function (original
            # closure/__class__ cells, zero recompilation risk)
            raise _Unchanged()
        ast.fix_missing_locations(new_tree)
        code = compile(new_tree, filename=f"<dy2static {fn.__name__}>",
                       mode="exec")
        import paddle_tpu.jit.dy2static as _self
        glb = dict(fn.__globals__)
        glb["__dy2st"] = _self
        if fn.__closure__:
            # closure cells SHADOW same-named module globals
            for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
                try:
                    glb[name] = cell.cell_contents
                except ValueError:
                    pass
        ns: dict = {}
        exec(code, glb, ns)
        converted = ns[fdef.name]
        converted = functools.wraps(fn)(converted)
        converted.__dy2static_converted__ = True
    except Exception:
        converted = fn
    _CONVERTED[fn] = converted
    return converted
