"""paddle_tpu.geometric — graph learning ops (reference:
/root/reference/python/paddle/geometric/__init__.py: segment math,
send_u_recv/send_ue_recv/send_uv message passing, reindex, sampling).

TPU-first: everything is jax.ops.segment_* / gather — XLA's sorted-segment
lowering replaces the reference's hand CUDA scatter kernels
(paddle/phi/kernels/gpu/graph_send_recv_kernel.cu)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, apply_op

__all__ = ["segment_sum", "segment_mean", "segment_min", "segment_max",
           "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
           "sample_neighbors"]


def _idx(t):
    arr = t._data if isinstance(t, Tensor) else jnp.asarray(t)
    return arr.astype(jnp.int32)


def _num_segments(segment_ids, count=None):
    if count is not None:
        return int(count)
    ids = np.asarray(segment_ids)
    return int(ids.max()) + 1 if ids.size else 0


def _segment(op_name, jax_op, fill=0.0):
    def op(data, segment_ids, name=None):
        ids = _idx(segment_ids)
        n = _num_segments(ids)

        def f(d):
            out = jax_op(d, ids, num_segments=n)
            if fill is not None:
                # empty segments → 0 (reference fills 0, not +-inf)
                counts = jax.ops.segment_sum(
                    jnp.ones(ids.shape[0]), ids, num_segments=n)
                shape = (n,) + (1,) * (d.ndim - 1)
                out = jnp.where(counts.reshape(shape) > 0, out, fill)
            return out

        return apply_op(f, data, _op_name=op_name)

    op.__name__ = op_name
    return op


segment_sum = _segment("segment_sum", jax.ops.segment_sum, fill=None)
segment_mean = _segment(
    "segment_mean",
    lambda d, ids, num_segments: jax.ops.segment_sum(
        d, ids, num_segments=num_segments)
    / jnp.maximum(jax.ops.segment_sum(
        jnp.ones(ids.shape[0], d.dtype), ids,
        num_segments=num_segments), 1.0).reshape(
            (num_segments,) + (1,) * (d.ndim - 1)))
segment_min = _segment("segment_min", jax.ops.segment_min)
segment_max = _segment("segment_max", jax.ops.segment_max)

_REDUCERS = {"sum": jax.ops.segment_sum, "mean": None,
             "min": jax.ops.segment_min, "max": jax.ops.segment_max}


def _reduce(msgs, dst, n, pool):
    if pool == "mean":
        s = jax.ops.segment_sum(msgs, dst, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones(dst.shape[0], msgs.dtype), dst,
                                  num_segments=n)
        return s / jnp.maximum(cnt, 1.0).reshape(
            (n,) + (1,) * (msgs.ndim - 1))
    red = _REDUCERS[pool]
    out = red(msgs, dst, num_segments=n)
    if pool in ("min", "max"):
        cnt = jax.ops.segment_sum(jnp.ones(dst.shape[0]), dst,
                                  num_segments=n)
        out = jnp.where(cnt.reshape((n,) + (1,) * (msgs.ndim - 1)) > 0,
                        out, 0.0)
    return out


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size=None, name=None):
    """Gather x[src] along edges, segment-reduce at dst
    (message_passing/send_recv.py:55)."""
    src, dst = _idx(src_index), _idx(dst_index)
    # reference semantics: out_size None → one row per input node
    n = int(out_size) if out_size is not None else int(x.shape[0])

    def f(a):
        return _reduce(a[src], dst, n, reduce_op)

    return apply_op(f, x, _op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size=None, name=None):
    """Combine x[src] with edge feature y, reduce at dst
    (send_recv.py:210)."""
    src, dst = _idx(src_index), _idx(dst_index)
    n = int(out_size) if out_size is not None else int(x.shape[0])
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op]

    def f(a, e):
        return _reduce(combine(a[src], e), dst, n, reduce_op)

    return apply_op(f, x, y, _op_name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op: str = "add",
            name=None):
    """Per-edge message x[src] ⊕ y[dst] (send_recv.py:413)."""
    src, dst = _idx(src_index), _idx(dst_index)
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op]

    def f(a, b):
        return combine(a[src], b[dst])

    return apply_op(f, x, y, _op_name="send_uv")


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global ids to local ids (reindex.py:25). Host-side: output
    shape is data-dependent (hash-map semantics), like the reference's
    CPU/GPU hashtable kernel."""
    xs = np.asarray(_idx(x))
    nb = np.asarray(_idx(neighbors))
    cnt = np.asarray(_idx(count))
    uniq = {}
    for v in xs.tolist():
        uniq.setdefault(v, len(uniq))
    out_nodes = list(xs.tolist())
    for v in nb.tolist():
        if v not in uniq:
            uniq[v] = len(uniq)
            out_nodes.append(v)
    reindex_src = np.array([uniq[v] for v in nb.tolist()], np.int32)
    dst = np.repeat(np.arange(len(xs), dtype=np.int32), cnt)
    return (Tensor(jnp.asarray(reindex_src)), Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(np.array(out_nodes, np.int32))))


def sample_neighbors(row, colptr, input_nodes, sample_size: int = -1,
                     eids=None, return_eids: bool = False,
                     perm_buffer=None, name=None):
    """CSC neighbor sampling (sampling/neighbors.py:26). Host-side RNG
    (data-dependent output size); seeded from numpy's global RNG so
    successive calls draw different subgraphs."""
    if return_eids:
        raise NotImplementedError("return_eids is not supported yet")
    r = np.asarray(_idx(row))
    cp = np.asarray(_idx(colptr))
    nodes = np.asarray(_idx(input_nodes))
    rng = np.random
    out_neighbors, out_count = [], []
    for v in nodes.tolist():
        beg, end = int(cp[v]), int(cp[v + 1])
        neigh = r[beg:end]
        if 0 <= sample_size < len(neigh):
            neigh = rng.choice(neigh, size=sample_size, replace=False)
        out_neighbors.extend(neigh.tolist())
        out_count.append(len(neigh))
    return (Tensor(jnp.asarray(np.array(out_neighbors, np.int32))),
            Tensor(jnp.asarray(np.array(out_count, np.int32))))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous-graph reindex (reindex.py:169): neighbors/count are
    per-edge-type lists sharing one id space; ids are compacted once
    across all types."""
    xs = np.asarray(_idx(x))
    uniq = {}
    for v in xs.tolist():
        uniq.setdefault(v, len(uniq))
    out_nodes = list(xs.tolist())
    reindex_srcs, dsts = [], []
    for nb_t, cnt_t in zip(neighbors, count):
        nb = np.asarray(_idx(nb_t))
        cnt = np.asarray(_idx(cnt_t))
        for v in nb.tolist():
            if v not in uniq:
                uniq[v] = len(uniq)
                out_nodes.append(v)
        reindex_srcs.append(np.array([uniq[v] for v in nb.tolist()],
                                     np.int32))
        dsts.append(np.repeat(np.arange(len(xs), dtype=np.int32), cnt))
    return (Tensor(jnp.asarray(np.concatenate(reindex_srcs))),
            Tensor(jnp.asarray(np.concatenate(dsts))),
            Tensor(jnp.asarray(np.array(out_nodes, np.int32))))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size: int = -1, eids=None,
                              return_eids: bool = False, name=None):
    """Weighted CSC neighbor sampling (sampling/neighbors.py:180):
    neighbors drawn without replacement proportionally to edge weight."""
    if return_eids:
        raise NotImplementedError("return_eids is not supported yet")
    r = np.asarray(_idx(row))
    w = np.asarray(edge_weight.numpy() if isinstance(edge_weight, Tensor)
                   else edge_weight, np.float64)
    cp = np.asarray(_idx(colptr))
    nodes = np.asarray(_idx(input_nodes))
    out_neighbors, out_count = [], []
    for v in nodes.tolist():
        beg, end = int(cp[v]), int(cp[v + 1])
        neigh = r[beg:end]
        wt = w[beg:end]
        if 0 <= sample_size < len(neigh):
            probs = wt / wt.sum() if wt.sum() > 0 else None
            idx = np.random.choice(len(neigh), size=sample_size,
                                   replace=False, p=probs)
            neigh = neigh[idx]
        out_neighbors.extend(neigh.tolist())
        out_count.append(len(neigh))
    return (Tensor(jnp.asarray(np.array(out_neighbors, np.int32))),
            Tensor(jnp.asarray(np.array(out_count, np.int32))))


__all__ += ["reindex_heter_graph", "weighted_sample_neighbors"]
