"""Dataset types (reference: python/paddle/io/dataloader/dataset.py)."""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    """Samples are row slices of the given tensors (reference
    io/dataset.py TensorDataset). The tensor VALUES are snapshotted to
    host memory at construction: per-sample device slicing would
    dispatch one program per sample on an accelerator, making the data
    pipeline the bottleneck; host rows collate into one upload per
    batch."""

    def __init__(self, tensors: Sequence):
        lens = {t.shape[0] for t in tensors}
        if len(lens) != 1:
            raise ValueError("all tensors must share dim 0")
        self.tensors = list(tensors)
        import numpy as _np
        self._host = [_np.asarray(getattr(t, "_data", t))
                      for t in self.tensors]

    def __getitem__(self, idx):
        from ..framework.tensor import Tensor
        return tuple(Tensor(h[idx]) for h in self._host)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            sample = ds[idx]
            out.extend(sample if isinstance(sample, (tuple, list))
                       else [sample])
        return tuple(out)

    def __len__(self):
        return min(len(ds) for ds in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets: Sequence[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class ConcatDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds_idx == 0 else self.cum[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence,
                 generator=None) -> List[Subset]:
    lengths = list(lengths)
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        sizes = [int(np.floor(n * f)) for f in lengths]
        for i in range(n - sum(sizes)):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(len(dataset))
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out
