"""Samplers (reference: python/paddle/io/dataloader/sampler.py,
batch_sampler.py; DistributedBatchSampler distributed variant)."""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

__all__ = ["Sampler", "SequenceSampler", "RandomSampler",
           "WeightedRandomSampler", "SubsetRandomSampler", "BatchSampler",
           "DistributedBatchSampler"]


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights: Sequence[float], num_samples: int,
                 replacement: bool = True):
        super().__init__(None)
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices: Sequence[int]):
        super().__init__(None)
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else \
                SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch: List[int] = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards sample indices across data-parallel ranks (reference:
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler).
    Rank/world default from paddle_tpu.distributed environment."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_world_size, get_rank
            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        n = len(dataset)
        self.num_samples = (n + self.nranks - 1) // self.nranks if not \
            drop_last else n // self.nranks
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        if not self.drop_last:
            pad = self.total_size - len(indices)
            indices += indices[:pad]
        else:
            indices = indices[:self.total_size]
        local = indices[self.local_rank::self.nranks]
        batch = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size
