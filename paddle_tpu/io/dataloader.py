"""DataLoader (reference: python/paddle/io/reader.py:262 DataLoader;
worker machinery io/dataloader/dataloader_iter.py:154/:368 with shared-mem
queues + C++ blocking queues).

TPU-native: ``num_workers > 0`` runs real worker PROCESSES (fork) that
fetch + collate to numpy off the GIL — the reference's
_DataLoaderIterMultiProcess — with ordered reassembly, persistent
workers, worker_init_fn/seed semantics, and IterableDataset sharding via
``get_worker_info``. Conversion to device Tensors happens in the parent
(jax must not run in forked children). ``worker_mode="thread"`` keeps
the round-1 threaded prefetch for cheap/numpy-only pipelines. No
pin-memory/CUDA streams — jax transfers are async already.
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional

import numpy as np

from ..framework.tensor import Tensor
from ..resilience.faults import maybe_fail   # stdlib-only: fork-safe
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def get_worker_info():
    return getattr(_worker_info, "info", None)


def _np_collate(batch):
    """Worker-side collate: pure numpy (no jax in forked children).
    Mirrors default_collate_fn's structure handling; the parent converts
    leaves to Tensors with _to_tensor_tree."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        from .native import native_collate
        fast = native_collate(batch)
        return fast if fast is not None else np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        return tuple(_np_collate(list(s)) for s in zip(*batch))
    if isinstance(sample, dict):
        return {k: _np_collate([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return np.asarray(batch)


def _to_tensor_tree(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, tuple):
        return tuple(_to_tensor_tree(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _to_tensor_tree(v) for k, v in obj.items()}
    return obj


def _worker_loop(dataset, index_queue, data_queue, collate, init_fn,
                 wid, num_workers, seed, iterable_mode, batch_size,
                 drop_last):
    """Body of one worker process (reference: io/dataloader/worker.py
    _worker_loop): seeds RNG per worker, exposes get_worker_info(),
    runs worker_init_fn, then serves index-batches until the None
    sentinel (map datasets) or streams its shard (iterable datasets)."""
    import random as _random
    _worker_info.info = WorkerInfo(wid, num_workers, dataset)
    np.random.seed((seed + wid) % (2 ** 32))
    _random.seed(seed + wid)
    try:
        if init_fn is not None:
            init_fn(wid)
        if iterable_mode:
            seq = 0
            batch = []
            for sample in dataset:
                maybe_fail("io.dataloader.worker", wid=wid)
                if batch_size is None:
                    data_queue.put((wid, seq, sample))
                    seq += 1
                    continue
                batch.append(sample)
                if len(batch) == batch_size:
                    data_queue.put((wid, seq, collate(batch)))
                    seq += 1
                    batch = []
            if batch_size is not None and batch and not drop_last:
                data_queue.put((wid, seq, collate(batch)))
            data_queue.put((wid, None, None))  # this worker is done
            return
        while True:
            task = index_queue.get()
            if task is None:
                return
            bidx, indices = task
            # PTPU_FAULTS is inherited across the fork, so chaos tests
            # can kill a worker from the parent's environment
            maybe_fail("io.dataloader.worker", wid=wid)
            samples = [dataset[i] for i in indices]
            data_queue.put((wid, bidx, collate(samples)))
    except KeyboardInterrupt:
        pass
    except BaseException as e:  # surface worker crashes to the parent
        import traceback
        data_queue.put((wid, "error",
                        f"{type(e).__name__}: {e}\n"
                        f"{traceback.format_exc()}"))


def default_collate_fn(batch):
    """Stack a list of samples into batched Tensors
    (reference: io/dataloader/collate.py). Equal-shape numpy samples take
    the native multithreaded-memcpy path (csrc/data_feed.cc)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp
        return Tensor(jnp.stack([b._data for b in batch]))
    if isinstance(sample, np.ndarray):
        from .native import native_collate
        fast = native_collate(batch)
        return Tensor(fast if fast is not None else np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return tuple(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch])
                for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return Tensor(np.asarray(batch))


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler: Optional[BatchSampler] = None,
                 batch_size: int = 1, shuffle: bool = False,
                 drop_last: bool = False, collate_fn: Callable = None,
                 num_workers: int = 0, use_buffer_reader: bool = True,
                 prefetch_factor: int = 2, use_shared_memory: bool = True,
                 timeout: int = 0, worker_init_fn: Callable = None,
                 persistent_workers: bool = False,
                 worker_mode: Optional[str] = None):
        self.dataset = dataset
        self.num_workers = max(0, num_workers)
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.persistent_workers = persistent_workers
        if worker_mode not in (None, "process", "thread"):
            raise ValueError(f"worker_mode must be 'process' or "
                             f"'thread', got {worker_mode!r}")
        if worker_mode is None:
            # default collate has a numpy mirror safe for forked
            # children; a CUSTOM collate may build Tensors (jax), which
            # must not run post-fork -> default those to threads
            worker_mode = "process" \
                if self.collate_fn is default_collate_fn else "thread"
        if worker_mode == "process" \
                and "fork" not in mp.get_all_start_methods():
            # no fork (Windows; macOS default is spawn): spawn would
            # re-import jax and re-pickle the dataset in every child —
            # thread workers are the safe degradation
            worker_mode = "thread"
        self.worker_mode = worker_mode
        self._pool = None  # persistent map-style process pool
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self.batch_size = batch_size
        self.drop_last = drop_last
        if self._iterable_mode:
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif batch_size is None:
            self.batch_sampler = None
        else:
            self.batch_sampler = BatchSampler(dataset=dataset,
                                              shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _fetch(self, indices):
        # same fault point as the process workers: thread-mode and
        # in-process loaders are injectable through one name
        maybe_fail("io.dataloader.worker")
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _iter_iterable(self):
        if self.batch_size is None:
            for sample in self.dataset:
                yield sample
            return
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def __iter__(self):
        # batch-wait histogram: how long the consumer (the training
        # loop) blocked for each batch — THE input-pipeline health
        # metric; near-zero waits mean the loader keeps up, spikes mean
        # the accelerator starves
        from ..observability import default_registry
        hist = default_registry().histogram(
            "ptpu_io_batch_wait_seconds",
            "time the consumer blocked waiting for the next batch")
        it = self._iter_impl()
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            hist.observe(time.perf_counter() - t0)
            yield batch

    def _iter_impl(self):
        if self._iterable_mode:
            if self.num_workers > 0 and self.worker_mode == "process":
                yield from self._iter_proc_iterable()
            else:
                yield from self._iter_iterable()
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.collate_fn([self.dataset[i]])
            return
        if self.num_workers == 0:
            for indices in self.batch_sampler:
                yield self._fetch(indices)
            return
        if self.worker_mode == "process":
            # real worker processes: fetch + numpy-collate off the GIL
            yield from self._iter_proc_map()
            return
        # threaded prefetch pipeline (workers fetch+collate; bounded queue
        # keeps `prefetch_factor * num_workers` batches in flight)
        yield from self._iter_workers()

    # -- multiprocess workers ----------------------------------------------
    def _worker_collate(self):
        """Collate used INSIDE worker processes: the numpy mirror for
        the default (jax must not run in forked children); custom
        collate_fns run as-is and should return picklable numpy."""
        return _np_collate if self.collate_fn is default_collate_fn \
            else self.collate_fn

    def _base_seed(self):
        # host numpy RNG: advanced per epoch so reshuffles/augmentations
        # differ across epochs but are reproducible under np.random.seed
        return int(np.random.randint(0, 2 ** 31))

    def _start_pool(self):
        ctx = mp.get_context("fork")
        index_queue = ctx.Queue()
        data_queue = ctx.Queue()
        seed = self._base_seed()
        procs = []
        for wid in range(self.num_workers):
            p = ctx.Process(
                target=_worker_loop,
                args=(self.dataset, index_queue, data_queue,
                      self._worker_collate(), self.worker_init_fn, wid,
                      self.num_workers, seed, False, None, False),
                daemon=True)
            p.start()
            procs.append(p)
        return {"index": index_queue, "data": data_queue, "procs": procs,
                "epoch": 0, "done": set()}

    def _shutdown_pool(self, pool):
        for _ in pool["procs"]:
            pool["index"].put(None)
        for p in pool["procs"]:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()

    def __del__(self):
        if getattr(self, "_pool", None) is not None:
            try:
                self._shutdown_pool(self._pool)
            except Exception:
                pass
            self._pool = None

    def _get_result(self, pool):
        """Blocking data-queue read with crash detection (workers that
        finished their shard cleanly are in pool['done'], not crashes)."""
        wait = self.timeout or None
        while True:
            try:
                return pool["data"].get(timeout=wait or 5.0)
            except queue.Empty:
                if wait is not None:
                    raise RuntimeError(
                        f"DataLoader timed out after {self.timeout}s "
                        f"waiting for a worker batch")
                for wid, p in enumerate(pool["procs"]):
                    if not p.is_alive() and wid not in pool["done"]:
                        raise RuntimeError(
                            "DataLoader worker died unexpectedly")

    def _wrap(self, payload):
        # only the default collate's numpy output is auto-wrapped;
        # custom collate output passes through unchanged so the batch
        # type does not depend on num_workers/worker_mode
        return _to_tensor_tree(payload) \
            if self.collate_fn is default_collate_fn else payload

    def _iter_proc_map(self):
        pool = self._pool if self.persistent_workers and self._pool \
            else self._start_pool()
        if self.persistent_workers:
            self._pool = pool
        pool["epoch"] += 1
        epoch = pool["epoch"]
        ok = False
        try:
            max_inflight = self.prefetch_factor * self.num_workers
            tasks = enumerate(iter(self.batch_sampler))
            inflight = 0
            for bidx, indices in itertools.islice(tasks, max_inflight):
                pool["index"].put(((epoch, bidx), list(indices)))
                inflight += 1
            reorder = {}
            next_yield = 0
            while inflight:
                wid, tag, payload = self._get_result(pool)
                if tag == "error":
                    raise RuntimeError(
                        f"DataLoader worker {wid} failed:\n{payload}")
                tag_epoch, bidx = tag
                if tag_epoch != epoch:
                    continue  # stale result from an abandoned epoch
                reorder[bidx] = payload
                inflight -= 1
                for nbidx, nind in itertools.islice(tasks, 1):
                    pool["index"].put(((epoch, nbidx), list(nind)))
                    inflight += 1
                while next_yield in reorder:
                    yield self._wrap(reorder.pop(next_yield))
                    next_yield += 1
            ok = True
        finally:
            if not self.persistent_workers:
                self._shutdown_pool(pool)
            elif not ok:
                # abandoned epoch (break/error): in-flight results from
                # this epoch would pollute the retained pool only if we
                # could not distinguish epochs — we can (epoch tags) —
                # but a raised worker error leaves a dead worker: drop
                # the pool so the next epoch starts clean
                alive = all(p.is_alive() for p in pool["procs"])
                if not alive:
                    self._shutdown_pool(pool)
                    self._pool = None

    def _iter_proc_iterable(self):
        ctx = mp.get_context("fork")
        # bounded queue = backpressure: workers stall instead of
        # buffering the whole dataset when the consumer is slower
        data_queue = ctx.Queue(
            maxsize=max(2, self.prefetch_factor * self.num_workers))
        seed = self._base_seed()
        procs = []
        for wid in range(self.num_workers):
            p = ctx.Process(
                target=_worker_loop,
                args=(self.dataset, None, data_queue,
                      self._worker_collate(), self.worker_init_fn, wid,
                      self.num_workers, seed, True, self.batch_size,
                      self.drop_last),
                daemon=True)
            p.start()
            procs.append(p)
        pool = {"data": data_queue, "procs": procs, "done": set()}
        try:
            while len(pool["done"]) < self.num_workers:
                wid, seq, payload = self._get_result(pool)
                if seq == "error":
                    raise RuntimeError(
                        f"DataLoader worker {wid} failed:\n{payload}")
                if seq is None:
                    pool["done"].add(wid)
                    continue
                yield self._wrap(payload)
        finally:
            for p in procs:
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()

    def _iter_workers(self):
        max_inflight = self.prefetch_factor * self.num_workers
        pool = ThreadPoolExecutor(max_workers=self.num_workers)
        if self.worker_init_fn is not None:
            for wid in range(self.num_workers):
                self.worker_init_fn(wid)
        try:
            batches = iter(self.batch_sampler)
            inflight = []
            for indices in itertools.islice(batches, max_inflight):
                inflight.append(pool.submit(self._fetch, indices))
            for indices in batches:
                fut = inflight.pop(0)
                inflight.append(pool.submit(self._fetch, indices))
                yield fut.result()
            for fut in inflight:
                yield fut.result()
        finally:
            pool.shutdown(wait=False, cancel_futures=True)
