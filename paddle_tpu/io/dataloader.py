"""DataLoader (reference: python/paddle/io/reader.py:262 DataLoader;
worker machinery io/dataloader/dataloader_iter.py:154/:368 with shared-mem
queues + C++ blocking queues).

TPU-native: multiprocessing workers feed index-batches through a process
pool; collation produces numpy batches, converted to Tensors on the default
device. No pin-memory/CUDA streams — jax transfers are async already.
"""
from __future__ import annotations

import itertools
import queue
import threading
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from typing import Any, Callable, Optional

import numpy as np

from ..framework.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    """Stack a list of samples into batched Tensors
    (reference: io/dataloader/collate.py). Equal-shape numpy samples take
    the native multithreaded-memcpy path (csrc/data_feed.cc)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp
        return Tensor(jnp.stack([b._data for b in batch]))
    if isinstance(sample, np.ndarray):
        from .native import native_collate
        fast = native_collate(batch)
        return Tensor(fast if fast is not None else np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return tuple(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch])
                for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return Tensor(np.asarray(batch))


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler: Optional[BatchSampler] = None,
                 batch_size: int = 1, shuffle: bool = False,
                 drop_last: bool = False, collate_fn: Callable = None,
                 num_workers: int = 0, use_buffer_reader: bool = True,
                 prefetch_factor: int = 2, use_shared_memory: bool = True,
                 timeout: int = 0, worker_init_fn: Callable = None,
                 persistent_workers: bool = False):
        self.dataset = dataset
        self.num_workers = max(0, num_workers)
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self.batch_size = batch_size
        self.drop_last = drop_last
        if self._iterable_mode:
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif batch_size is None:
            self.batch_sampler = None
        else:
            self.batch_sampler = BatchSampler(dataset=dataset,
                                              shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _iter_iterable(self):
        if self.batch_size is None:
            for sample in self.dataset:
                yield sample
            return
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def __iter__(self):
        if self._iterable_mode:
            yield from self._iter_iterable()
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.collate_fn([self.dataset[i]])
            return
        if self.num_workers == 0:
            for indices in self.batch_sampler:
                yield self._fetch(indices)
            return
        # threaded prefetch pipeline (workers fetch+collate; bounded queue
        # keeps `prefetch_factor * num_workers` batches in flight)
        yield from self._iter_workers()

    def _iter_workers(self):
        max_inflight = self.prefetch_factor * self.num_workers
        pool = ThreadPoolExecutor(max_workers=self.num_workers)
        if self.worker_init_fn is not None:
            for wid in range(self.num_workers):
                self.worker_init_fn(wid)
        try:
            batches = iter(self.batch_sampler)
            inflight = []
            for indices in itertools.islice(batches, max_inflight):
                inflight.append(pool.submit(self._fetch, indices))
            for indices in batches:
                fut = inflight.pop(0)
                inflight.append(pool.submit(self._fetch, indices))
                yield fut.result()
            for fut in inflight:
                yield fut.result()
        finally:
            pool.shutdown(wait=False, cancel_futures=True)
