"""Data loading (reference: python/paddle/io/ — reader.py:262 DataLoader,
dataloader/dataloader_iter.py multi-process workers).

TPU-native design: the input pipeline is host-side; workers are a
thread/process pool feeding a bounded prefetch queue, and batches are
device_put asynchronously so the host overlaps with TPU compute (the
reference's pin-memory + CUDA-stream copy machinery has no TPU analog —
XLA transfers are already async).
"""
from .dataset import (Dataset, IterableDataset, TensorDataset,
                      ComposeDataset, ChainDataset, Subset, random_split,
                      ConcatDataset)
from .sampler import (Sampler, SequenceSampler, RandomSampler,
                      WeightedRandomSampler, BatchSampler,
                      DistributedBatchSampler, SubsetRandomSampler)
from .dataloader import DataLoader, default_collate_fn, get_worker_info

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "Subset", "random_split", "ConcatDataset",
           "Sampler", "SequenceSampler", "RandomSampler",
           "WeightedRandomSampler", "BatchSampler",
           "DistributedBatchSampler", "SubsetRandomSampler", "DataLoader",
           "default_collate_fn", "get_worker_info"]
