"""ctypes bindings + lazy build of the native data-feed library
(csrc/data_feed.cc). Reference analog: the C++ reader/blocking-queue stack
under /root/reference/paddle/fluid/operators/reader/ (here a small C ABI
consumed without pybind11)."""
from __future__ import annotations

import ctypes
import threading
from typing import List, Optional

import numpy as np

_lock = threading.Lock()
_lib = None
_build_failed = False


def get_lib():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        from ..utils.native_build import build_native_so
        so = build_native_so("data_feed.cc", "libptfeed.so")
        if so is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(so)
        lib.ptq_create.restype = ctypes.c_void_p
        lib.ptq_create.argtypes = [ctypes.c_size_t]
        lib.ptq_destroy.argtypes = [ctypes.c_void_p]
        lib.ptq_close.argtypes = [ctypes.c_void_p]
        lib.ptq_push.restype = ctypes.c_int
        lib.ptq_push.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_size_t, ctypes.c_int]
        lib.ptq_pop.restype = ctypes.c_int64
        lib.ptq_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                ctypes.c_size_t, ctypes.c_int]
        lib.ptq_size.restype = ctypes.c_int64
        lib.ptq_size.argtypes = [ctypes.c_void_p]
        lib.pt_parallel_collate.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
        lib.pt_gather_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int]
        _lib = lib
        return _lib


class BlockingQueue:
    """Native bounded byte queue (C++ blocking_queue analog)."""

    def __init__(self, capacity: int = 8):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native data_feed library unavailable")
        self._lib = lib
        self._h = lib.ptq_create(capacity)

    def push(self, data: bytes, timeout_ms: int = -1) -> int:
        return self._lib.ptq_push(self._h, data, len(data), timeout_ms)

    def pop(self, maxbytes: int, timeout_ms: int = -1) -> Optional[bytes]:
        buf = ctypes.create_string_buffer(maxbytes)
        n = self._lib.ptq_pop(self._h, buf, maxbytes, timeout_ms)
        if n <= 0:
            return None
        return buf.raw[:n]

    def close(self):
        self._lib.ptq_close(self._h)

    def __len__(self):
        return self._lib.ptq_size(self._h)

    def __del__(self):
        try:
            self._lib.ptq_destroy(self._h)
        except Exception:
            pass


def native_collate(samples: List[np.ndarray]) -> Optional[np.ndarray]:
    """Stack equal-shape contiguous samples with multithreaded memcpy;
    None when the fast path does not apply."""
    lib = get_lib()
    if lib is None or not samples:
        return None
    first = samples[0]
    if not isinstance(first, np.ndarray):
        return None
    shape, dtype = first.shape, first.dtype
    if dtype == object:
        return None
    for s in samples:
        if not isinstance(s, np.ndarray) or s.shape != shape or \
                s.dtype != dtype or not s.flags.c_contiguous:
            return None
    n = len(samples)
    out = np.empty((n,) + shape, dtype)
    sample_bytes = first.nbytes
    if sample_bytes == 0:
        return out
    ptrs = (ctypes.c_void_p * n)(
        *[s.ctypes.data_as(ctypes.c_void_p).value for s in samples])
    lib.pt_parallel_collate(out.ctypes.data_as(ctypes.c_void_p), ptrs, n,
                            sample_bytes, min(8, max(1, n // 16)))
    return out


def native_gather_rows(src: np.ndarray, indices) -> Optional[np.ndarray]:
    """batch = src[indices] with multithreaded row gather."""
    lib = get_lib()
    if lib is None or not isinstance(src, np.ndarray) or \
            not src.flags.c_contiguous or src.ndim < 1:
        return None
    idx = np.ascontiguousarray(np.asarray(indices, np.int64))
    row_bytes = src[0].nbytes if len(src) else 0
    out = np.empty((len(idx),) + src.shape[1:], src.dtype)
    if row_bytes:
        lib.pt_gather_rows(
            out.ctypes.data_as(ctypes.c_void_p),
            src.ctypes.data_as(ctypes.c_void_p),
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(idx), row_bytes, min(8, max(1, len(idx) // 64)))
    return out
