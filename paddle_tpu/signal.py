"""Signal processing: frame / overlap_add / stft / istft.

Reference: python/paddle/signal.py (frame:24, overlap_add:131, stft:201,
istft:365 — backed by phi frame/overlap_add kernels + fft). TPU-native:
framing is a gather/reshape XLA fuses for free; FFTs are native HLO.
"""
from __future__ import annotations

import jax.numpy as jnp

from .framework.tensor import Tensor, apply_op

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _frame_impl(a, frame_length, hop_length, axis):
    if axis not in (0, -1):
        raise ValueError("axis must be 0 or -1")
    if hop_length <= 0:
        raise ValueError(f"hop_length must be positive, got {hop_length}")
    n = a.shape[axis]
    if frame_length > n:
        raise ValueError(
            f"frame_length ({frame_length}) exceeds signal length ({n}) "
            f"on axis {axis}")
    num_frames = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(num_frames) * hop_length
    offs = jnp.arange(frame_length)
    if axis == -1:
        idx = starts[:, None] + offs[None, :]          # [F, L]
        out = jnp.take(a, idx, axis=a.ndim - 1)        # [..., F, L]
        return jnp.swapaxes(out, -1, -2)               # [..., L, F]
    idx = starts[None, :] + offs[:, None]              # [L, F]
    return jnp.take(a, idx, axis=0)                    # [L, F, ...]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames off the signal (paddle.signal.frame)."""
    return apply_op(
        lambda a: _frame_impl(a, frame_length, hop_length, axis), _t(x),
        _op_name="frame")


def _overlap_add_impl(a, hop_length, axis):
    if axis not in (0, -1):
        raise ValueError("axis must be 0 or -1")
    if axis == 0:
        a = jnp.moveaxis(a, 1, -1)
        a = jnp.moveaxis(a, 0, -2)  # [..., L, F] ordering
        res = _overlap_add_impl(a, hop_length, -1)
        return jnp.moveaxis(res, -1, 0)
    frame_length, num_frames = a.shape[-2], a.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    batch = a.shape[:-2]
    out = jnp.zeros(batch + (out_len,), dtype=a.dtype)
    idx = (jnp.arange(num_frames)[:, None] * hop_length +
           jnp.arange(frame_length)[None, :]).reshape(-1)
    frames = jnp.moveaxis(a, -1, -2).reshape(batch + (-1,))  # [..., F*L]
    return out.at[..., idx].add(frames)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Reconstruct a signal from framed slices (paddle.signal.overlap_add)."""
    return apply_op(lambda a: _overlap_add_impl(a, hop_length, axis), _t(x),
                    _op_name="overlap_add")


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform; returns [..., n_fft//2+1, F] complex
    (onesided) matching the reference contract."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    xt = _t(x)
    if window is not None:
        w = _t(window)._data.astype(jnp.float32)
    else:
        w = jnp.ones((win_length,), dtype=jnp.float32)
    if win_length < n_fft:  # center-pad window to n_fft
        lpad = (n_fft - win_length) // 2
        w = jnp.pad(w, (lpad, n_fft - win_length - lpad))

    def _stft(a):
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        frames = _frame_impl(a, n_fft, hop_length, -1)   # [..., n_fft, F]
        frames = frames * w[:, None]
        fftfn = jnp.fft.rfft if onesided else jnp.fft.fft
        spec = fftfn(frames, axis=-2)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return spec

    return apply_op(_stft, xt, _op_name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with least-squares window compensation."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    xt = _t(x)
    if window is not None:
        w = _t(window)._data.astype(jnp.float32)
    else:
        w = jnp.ones((win_length,), dtype=jnp.float32)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        w = jnp.pad(w, (lpad, n_fft - win_length - lpad))

    def _istft(spec):
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        ifftfn = jnp.fft.irfft if onesided else jnp.fft.ifft
        frames = ifftfn(spec, n=n_fft, axis=-2)          # [..., n_fft, F]
        if not return_complex:
            frames = frames.real if jnp.iscomplexobj(frames) else frames
        frames = frames * w[:, None]
        sig = _overlap_add_impl(frames, hop_length, -1)
        wsq = jnp.tile(
            (w * w)[:, None], (1, spec.shape[-1]))       # [n_fft, F]
        denom = _overlap_add_impl(wsq, hop_length, -1)
        sig = sig / jnp.where(denom > 1e-11, denom, 1.0)
        if center:
            sig = sig[..., n_fft // 2:]
            end = length if length is not None else sig.shape[-1] - n_fft // 2
            sig = sig[..., :end]
        elif length is not None:
            sig = sig[..., :length]
        return sig

    return apply_op(_istft, xt, _op_name="istft")
