"""paddle.tensor namespace alias."""
from . import ops as tensor
