"""paddle.hub parity: list/help/load entrypoints from a hubconf.py in a
local directory or github-style repo dir (reference: python/paddle/hub.py).
Network fetch is gated off (zero-egress environments); local sources work
fully."""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

MODULE_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {MODULE_HUBCONF} in {repo_dir}")
    # unique module name per repo so repeated loads from different repos
    # never alias each other in sys.modules
    mod_name = f"paddle_tpu_hubconf_{abs(hash(os.path.abspath(path)))}"
    spec = importlib.util.spec_from_file_location(mod_name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[mod_name] = mod
    spec.loader.exec_module(mod)
    return mod


def _resolve(repo_dir, source):
    if source not in ("local", "github", "gitee"):
        raise ValueError(
            f"unknown source {source!r}: expected local/github/gitee")
    if source != "local":
        raise RuntimeError(
            "remote hub sources are unavailable in this build (no network "
            "egress); clone the repo and use source='local'")
    return repo_dir


def list(repo_dir, source="github", force_reload=False):  # noqa: A001
    """List callable entrypoints exposed by the repo's hubconf."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):  # noqa: A001
    """Return the docstring of one entrypoint."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    entry = getattr(mod, model, None)
    if entry is None or not callable(entry):
        raise RuntimeError(f"no callable entrypoint {model!r} in hubconf")
    return entry.__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    """Instantiate an entrypoint: hub.load(dir, 'resnet50', source='local')."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    entry = getattr(mod, model, None)
    if entry is None or not callable(entry):
        raise RuntimeError(f"no callable entrypoint {model!r} in hubconf")
    return entry(**kwargs)
