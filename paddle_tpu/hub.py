"""paddle.hub parity: list/help/load entrypoints from a hubconf.py in a
local directory or a github/gitee repo (reference: python/paddle/hub.py
_get_cache_or_reload). Remote repos resolve to an archive URL fetched
through the same download cache the vision zoo uses
(utils/download.py) — ``file://`` archive URLs are first-class, so
air-gapped clusters mirror hub repos on shared storage."""
from __future__ import annotations

import importlib.util
import os
import shutil
import sys
import zipfile

__all__ = ["list", "help", "load"]

MODULE_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {MODULE_HUBCONF} in {repo_dir}")
    # unique module name per repo so repeated loads from different repos
    # never alias each other in sys.modules
    mod_name = f"paddle_tpu_hubconf_{abs(hash(os.path.abspath(path)))}"
    spec = importlib.util.spec_from_file_location(mod_name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[mod_name] = mod
    spec.loader.exec_module(mod)
    return mod


def _archive_url(repo, source):
    """'owner/repo[:branch]' -> the host's source-archive zip URL; a
    full URL (any scheme, incl. file://) passes through untouched."""
    if "://" in repo:
        return repo
    name, _, branch = repo.partition(":")
    branch = branch or "main"
    if source == "github":
        return f"https://github.com/{name}/archive/{branch}.zip"
    # gitee serves source archives under /repository/archive/
    return f"https://gitee.com/{name}/repository/archive/{branch}.zip"


def _resolve(repo_dir, source, force_reload=False):
    if source not in ("local", "github", "gitee"):
        raise ValueError(
            f"unknown source {source!r}: expected local/github/gitee")
    if source == "local":
        return repo_dir
    from .utils.download import get_path_from_url, WEIGHTS_HOME
    root = os.path.join(os.path.dirname(WEIGHTS_HOME), "hub")
    # force_reload bypasses the archive cache too — a moved branch tag
    # must re-fetch, not re-extract the stale zip
    archive = get_path_from_url(_archive_url(repo_dir, source), root,
                                check_exist=not force_reload)
    edir = archive + ".extracted"
    if force_reload and os.path.isdir(edir):
        shutil.rmtree(edir, ignore_errors=True)
    if not os.path.isdir(edir):
        # per-process tmp + tolerate a concurrent winner: hub caches
        # live on shared storage (air-gapped mirrors), so two jobs may
        # extract the same archive at once
        tmp = f"{edir}.tmp.{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        with zipfile.ZipFile(archive) as z:
            z.extractall(tmp)
        try:
            os.replace(tmp, edir)
        except OSError:
            if not os.path.isdir(edir):
                raise
            shutil.rmtree(tmp, ignore_errors=True)
    if os.path.exists(os.path.join(edir, MODULE_HUBCONF)):
        return edir
    # github/gitee archives nest everything under repo-branch/
    for sub in sorted(os.listdir(edir)):
        cand = os.path.join(edir, sub)
        if os.path.isdir(cand) and \
                os.path.exists(os.path.join(cand, MODULE_HUBCONF)):
            return cand
    raise FileNotFoundError(
        f"no {MODULE_HUBCONF} in archive from {repo_dir!r}")


def list(repo_dir, source="github", force_reload=False):  # noqa: A001
    """List callable entrypoints exposed by the repo's hubconf."""
    mod = _load_hubconf(_resolve(repo_dir, source, force_reload))
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):  # noqa: A001
    """Return the docstring of one entrypoint."""
    mod = _load_hubconf(_resolve(repo_dir, source, force_reload))
    entry = getattr(mod, model, None)
    if entry is None or not callable(entry):
        raise RuntimeError(f"no callable entrypoint {model!r} in hubconf")
    return entry.__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    """Instantiate an entrypoint: hub.load(dir, 'resnet50',
    source='local'), or hub.load('owner/repo:branch', 'resnet50') with
    the archive fetched through the weights download cache."""
    mod = _load_hubconf(_resolve(repo_dir, source, force_reload))
    entry = getattr(mod, model, None)
    if entry is None or not callable(entry):
        raise RuntimeError(f"no callable entrypoint {model!r} in hubconf")
    return entry(**kwargs)
