"""Metrics (reference: python/paddle/metric/metrics.py —
Accuracy/Precision/Recall/Auc)."""
from __future__ import annotations

from typing import List, Sequence, Union

import numpy as np

from ..framework.tensor import Tensor
from ..ops.manipulation import topk as topk_op

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(x._data) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        correct = idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0] if c.ndim > 0 else 1
        res = []
        for k in self.topk:
            acc_k = c[..., :k].sum(-1).mean()
            self.total[self.topk.index(k)] += float(
                c[..., :k].sum(-1).sum())
            self.count[self.topk.index(k)] += num
            res.append(float(acc_k))
        return res[0] if len(res) == 1 else res

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, 1]
        l = _np(labels).reshape(-1)
        bins = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                       self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # trapezoid over threshold bins, descending threshold
        pos = self._stat_pos[::-1].cumsum()
        neg = self._stat_neg[::-1].cumsum()
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (python/paddle/metric/metrics.py:accuracy)."""
    pred = _np(input)
    lab = _np(label).reshape(-1)
    idx = np.argsort(-pred, axis=-1)[:, :k]
    correct_mask = (idx == lab[:, None]).any(axis=1)
    return Tensor(np.asarray(correct_mask.mean(), np.float32))
