"""static.nn — layer-building functions for static graphs.

Reference: python/paddle/static/nn/common.py (fc, conv2d, batch_norm,
embedding, ...). Each call creates eager parameters (registered with the
current Program) and records the compute through the nn.functional ops —
the same kernels as dynamic mode, only deferred.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..nn import functional as F
from ..nn import initializer as init_mod
from .graph import create_parameter, default_main_program

__all__ = ["fc", "conv2d", "batch_norm", "embedding", "layer_norm",
           "dropout", "prelu", "sequence_softmax"]


def _act(x, activation):
    if activation is None:
        return x
    fn = getattr(F, activation, None)
    if fn is None:
        raise ValueError(f"unknown activation '{activation}'")
    return fn(x)


def fc(x, size: int, num_flatten_dims: int = 1, weight_attr=None,
       bias_attr=None, activation=None, name=None):
    """static.nn.fc (static/nn/common.py:31): flattens dims
    [num_flatten_dims:] into the feature dim; output shape =
    x.shape[:num_flatten_dims] + [size]."""
    if num_flatten_dims == -1:
        num_flatten_dims = len(x.shape) - 1
    tail = x.shape[num_flatten_dims:]
    if any(d < 0 for d in tail):
        raise ValueError("fc flattened feature dims must be static")
    in_dim = int(np.prod(tail)) if tail else 1
    if len(tail) != 1:
        x = x.reshape(list(x.shape[:num_flatten_dims]) + [in_dim])
    w = create_parameter([in_dim, size], dtype=x.dtype.name,
                         default_initializer=init_mod.XavierNormal(),
                         name=None if name is None else f"{name}.w_0")
    out = F.linear(x, w)
    if bias_attr is not False:
        b = create_parameter([size], dtype=x.dtype.name, is_bias=True,
                             name=None if name is None else f"{name}.b_0")
        out = out + b
    return _act(out, activation)


def conv2d(input, num_filters: int, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCHW"):
    if isinstance(filter_size, int):
        filter_size = (filter_size, filter_size)
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    if cin < 0:
        raise ValueError("conv2d input channels must be static")
    w = create_parameter(
        [num_filters, cin // groups, *filter_size], dtype=input.dtype.name,
        default_initializer=init_mod.KaimingUniform())
    b = None
    if bias_attr is not False:
        b = create_parameter([num_filters], dtype=input.dtype.name,
                             is_bias=True)
    out = F.conv2d(input, w, bias=b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   data_format=data_format)
    return _act(out, act)


def batch_norm(input, act=None, is_test: bool = False, momentum=0.9,
               epsilon=1e-5, param_attr=None, bias_attr=None,
               data_layout="NCHW", name=None, moving_mean_name=None,
               moving_variance_name=None):
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    if c < 0:
        raise ValueError("batch_norm channel dim must be static")
    dt = input.dtype.name
    scale = create_parameter([c], dtype=dt,
                             default_initializer=init_mod.Constant(1.0))
    bias = create_parameter([c], dtype=dt, is_bias=True)
    mean = create_parameter([c], dtype=dt,
                            default_initializer=init_mod.Constant(0.0))
    var = create_parameter([c], dtype=dt,
                           default_initializer=init_mod.Constant(1.0))
    mean.stop_gradient = True
    var.stop_gradient = True
    # static graphs run inference-style normalization against the captured
    # running stats (training-mode stat updates belong to dynamic mode)
    out = F.batch_norm(input, mean, var, weight=scale, bias=bias,
                       training=False, momentum=momentum,
                       epsilon=epsilon, data_format=data_layout)
    return _act(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = input.shape[begin_norm_axis:]
    if any(d < 0 for d in shape):
        raise ValueError("layer_norm normalized dims must be static")
    dt = input.dtype.name
    n = int(np.prod(shape))
    g = create_parameter([n], dtype=dt,
                         default_initializer=init_mod.Constant(1.0)) \
        if scale else None
    b = create_parameter([n], dtype=dt, is_bias=True) if shift else None
    flat = input.reshape(input.shape[:begin_norm_axis] + [n]) \
        if len(shape) > 1 else input
    out = F.layer_norm(flat, normalized_shape=[n], weight=g, bias=b,
                       epsilon=epsilon)
    if len(shape) > 1:
        out = out.reshape(input.shape[:begin_norm_axis] + list(shape))
    return _act(out, act)


def embedding(input, size, is_sparse: bool = False, padding_idx=None,
              param_attr=None, dtype="float32"):
    w = create_parameter(list(size), dtype=dtype,
                         default_initializer=init_mod.XavierNormal())
    return F.embedding(input, w, padding_idx=padding_idx)


def dropout(x, dropout_prob=0.5, is_test=False, seed=None, name=None):
    return F.dropout(x, p=dropout_prob, training=not is_test)


def prelu(x, mode="all", param_attr=None, name=None):
    n = 1 if mode == "all" else x.shape[1]
    alpha = create_parameter([n], dtype=x.dtype.name,
                             default_initializer=init_mod.Constant(0.25))
    return F.prelu(x, alpha)


def sequence_softmax(input, axis=-1):
    return F.softmax(input, axis=axis)
