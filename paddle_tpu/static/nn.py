"""static.nn — layer-building functions for static graphs.

Reference: python/paddle/static/nn/common.py (fc, conv2d, batch_norm,
embedding, ...). Each call creates eager parameters (registered with the
current Program) and records the compute through the nn.functional ops —
the same kernels as dynamic mode, only deferred.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..nn import functional as F
from ..nn import initializer as init_mod
from .graph import create_parameter, default_main_program

__all__ = ["fc", "conv2d", "batch_norm", "embedding", "layer_norm",
           "dropout", "prelu", "sequence_softmax", "conv2d_transpose",
           "conv3d", "conv3d_transpose", "group_norm", "instance_norm",
           "data_norm", "spectral_norm", "bilinear_tensor_product",
           "deform_conv2d", "row_conv", "sequence_pool",
           "sequence_first_step", "sequence_last_step",
           "sequence_expand", "sequence_conv", "sparse_embedding",
           "nce", "cond", "case", "switch_case", "while_loop",
           "static_pylayer", "py_func"]


def _act(x, activation):
    if activation is None:
        return x
    fn = getattr(F, activation, None)
    if fn is None:
        raise ValueError(f"unknown activation '{activation}'")
    return fn(x)


def fc(x, size: int, num_flatten_dims: int = 1, weight_attr=None,
       bias_attr=None, activation=None, name=None):
    """static.nn.fc (static/nn/common.py:31): flattens dims
    [num_flatten_dims:] into the feature dim; output shape =
    x.shape[:num_flatten_dims] + [size]."""
    if num_flatten_dims == -1:
        num_flatten_dims = len(x.shape) - 1
    tail = x.shape[num_flatten_dims:]
    if any(d < 0 for d in tail):
        raise ValueError("fc flattened feature dims must be static")
    in_dim = int(np.prod(tail)) if tail else 1
    if len(tail) != 1:
        x = x.reshape(list(x.shape[:num_flatten_dims]) + [in_dim])
    w = create_parameter([in_dim, size], dtype=x.dtype.name,
                         default_initializer=init_mod.XavierNormal(),
                         name=None if name is None else f"{name}.w_0")
    out = F.linear(x, w)
    if bias_attr is not False:
        b = create_parameter([size], dtype=x.dtype.name, is_bias=True,
                             name=None if name is None else f"{name}.b_0")
        out = out + b
    return _act(out, activation)


def conv2d(input, num_filters: int, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCHW"):
    if isinstance(filter_size, int):
        filter_size = (filter_size, filter_size)
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    if cin < 0:
        raise ValueError("conv2d input channels must be static")
    w = create_parameter(
        [num_filters, cin // groups, *filter_size], dtype=input.dtype.name,
        default_initializer=init_mod.KaimingUniform())
    b = None
    if bias_attr is not False:
        b = create_parameter([num_filters], dtype=input.dtype.name,
                             is_bias=True)
    out = F.conv2d(input, w, bias=b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   data_format=data_format)
    return _act(out, act)


def batch_norm(input, act=None, is_test: bool = False, momentum=0.9,
               epsilon=1e-5, param_attr=None, bias_attr=None,
               data_layout="NCHW", name=None, moving_mean_name=None,
               moving_variance_name=None):
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    if c < 0:
        raise ValueError("batch_norm channel dim must be static")
    dt = input.dtype.name
    scale = create_parameter([c], dtype=dt,
                             default_initializer=init_mod.Constant(1.0))
    bias = create_parameter([c], dtype=dt, is_bias=True)
    mean = create_parameter([c], dtype=dt,
                            default_initializer=init_mod.Constant(0.0))
    var = create_parameter([c], dtype=dt,
                           default_initializer=init_mod.Constant(1.0))
    mean.stop_gradient = True
    var.stop_gradient = True
    # static graphs run inference-style normalization against the captured
    # running stats (training-mode stat updates belong to dynamic mode)
    out = F.batch_norm(input, mean, var, weight=scale, bias=bias,
                       training=False, momentum=momentum,
                       epsilon=epsilon, data_format=data_layout)
    return _act(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = input.shape[begin_norm_axis:]
    if any(d < 0 for d in shape):
        raise ValueError("layer_norm normalized dims must be static")
    dt = input.dtype.name
    n = int(np.prod(shape))
    g = create_parameter([n], dtype=dt,
                         default_initializer=init_mod.Constant(1.0)) \
        if scale else None
    b = create_parameter([n], dtype=dt, is_bias=True) if shift else None
    flat = input.reshape(input.shape[:begin_norm_axis] + [n]) \
        if len(shape) > 1 else input
    out = F.layer_norm(flat, normalized_shape=[n], weight=g, bias=b,
                       epsilon=epsilon)
    if len(shape) > 1:
        out = out.reshape(input.shape[:begin_norm_axis] + list(shape))
    return _act(out, act)


def embedding(input, size, is_sparse: bool = False, padding_idx=None,
              param_attr=None, dtype="float32"):
    w = create_parameter(list(size), dtype=dtype,
                         default_initializer=init_mod.XavierNormal())
    return F.embedding(input, w, padding_idx=padding_idx)


def dropout(x, dropout_prob=0.5, is_test=False, seed=None, name=None):
    return F.dropout(x, p=dropout_prob, training=not is_test)


def prelu(x, mode="all", param_attr=None, name=None):
    n = 1 if mode == "all" else x.shape[1]
    alpha = create_parameter([n], dtype=x.dtype.name,
                             default_initializer=init_mod.Constant(0.25))
    return F.prelu(x, alpha)


def sequence_softmax(input, axis=-1):
    return F.softmax(input, axis=axis)


# ---------------------------------------------------------------------------
# long-tail static.nn parity (static/nn/common.py + control_flow.py)
# ---------------------------------------------------------------------------

def conv2d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     groups=1, param_attr=None, bias_attr=None, act=None,
                     data_format="NCHW", name=None, output_size=None):
    if isinstance(filter_size, int):
        filter_size = (filter_size, filter_size)
    cin = input.shape[1]
    w = create_parameter([cin, num_filters // groups, *filter_size],
                         dtype=input.dtype.name,
                         default_initializer=init_mod.KaimingUniform())
    b = None if bias_attr is False else create_parameter(
        [num_filters], dtype=input.dtype.name, is_bias=True)
    out = F.conv2d_transpose(input, w, bias=b, stride=stride,
                             padding=padding, groups=groups,
                             output_size=output_size,
                             data_format=data_format)
    return _act(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, data_format="NCDHW", name=None):
    if isinstance(filter_size, int):
        filter_size = (filter_size,) * 3
    cin = input.shape[1]
    w = create_parameter([num_filters, cin // groups, *filter_size],
                         dtype=input.dtype.name,
                         default_initializer=init_mod.KaimingUniform())
    b = None if bias_attr is False else create_parameter(
        [num_filters], dtype=input.dtype.name, is_bias=True)
    out = F.conv3d(input, w, bias=b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   data_format=data_format)
    return _act(out, act)


def conv3d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     groups=1, param_attr=None, bias_attr=None, act=None,
                     data_format="NCDHW", name=None, output_size=None):
    if isinstance(filter_size, int):
        filter_size = (filter_size,) * 3
    cin = input.shape[1]
    w = create_parameter([cin, num_filters // groups, *filter_size],
                         dtype=input.dtype.name,
                         default_initializer=init_mod.KaimingUniform())
    b = None if bias_attr is False else create_parameter(
        [num_filters], dtype=input.dtype.name, is_bias=True)
    out = F.conv3d_transpose(input, w, bias=b, stride=stride,
                             padding=padding, groups=groups,
                             data_format=data_format)
    return _act(out, act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    c = input.shape[1]
    dt = input.dtype.name
    g = create_parameter([c], dtype=dt,
                         default_initializer=init_mod.Constant(1.0))
    b = create_parameter([c], dtype=dt, is_bias=True)
    out = F.group_norm(input, groups, weight=g, bias=b, epsilon=epsilon)
    return _act(out, act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    c = input.shape[1]
    dt = input.dtype.name
    g = create_parameter([c], dtype=dt,
                         default_initializer=init_mod.Constant(1.0))
    b = create_parameter([c], dtype=dt, is_bias=True)
    return F.instance_norm(input, weight=g, bias=b, eps=epsilon)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """Normalize with accumulated batch statistics (static/nn/common.py
    data_norm — the PS-era BN without affine params by default)."""
    # normalized with the CURRENT batch's statistics: without a stat-
    # update op in the recorded graph, frozen accumulators would pin
    # mean=0/var=1 forever; batch stats keep the op actually normalizing
    mean = input.mean(axis=0, keepdim=True)
    var = ((input - mean) ** 2).mean(axis=0, keepdim=True)
    out = (input - mean) / (var + epsilon).sqrt()
    return _act(out, act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    return F.spectral_norm(weight, dim=dim, power_iters=power_iters,
                           eps=eps)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    dx, dy = x.shape[-1], y.shape[-1]
    dt = x.dtype.name
    w = create_parameter([size, dx, dy], dtype=dt)
    b = None if bias_attr is False else create_parameter(
        [size], dtype=dt, is_bias=True)
    out = F.bilinear(x, y, w, b)
    return _act(out, act)


def deform_conv2d(input, offset, mask, num_filters, filter_size,
                  stride=1, padding=0, dilation=1, groups=1,
                  deformable_groups=1, im2col_step=1, param_attr=None,
                  bias_attr=None, name=None):
    from ..vision.ops import deform_conv2d as _dc
    if isinstance(filter_size, int):
        filter_size = (filter_size, filter_size)
    cin = input.shape[1]
    w = create_parameter([num_filters, cin // groups, *filter_size],
                         dtype=input.dtype.name)
    b = None if bias_attr is False else create_parameter(
        [num_filters], dtype=input.dtype.name, is_bias=True)
    return _dc(input, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution over [B, T, D] (static/nn/common.py)."""
    d = input.shape[-1]
    w = create_parameter([future_context_size + 1, d],
                         dtype=input.dtype.name)
    from ..framework.tensor import apply_op
    import jax.numpy as jnp

    def f(a, k):
        T = a.shape[1]
        ctx = k.shape[0]
        pad = jnp.pad(a, ((0, 0), (0, ctx - 1), (0, 0)))
        out = 0.0
        for i in range(ctx):
            out = out + pad[:, i:i + T, :] * k[i]
        return out
    out = apply_op(f, input, w, _op_name="row_conv")
    return _act(out, act)


# -- legacy sequence ops on padded [B, T, D] batches ----------------------

def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):
    pt = pool_type.lower()
    if pt == "sum":
        return input.sum(axis=1)
    if pt in ("average", "avg", "mean"):
        return input.mean(axis=1)
    if pt == "max":
        return input.max(axis=1)
    if pt == "sqrt":
        import math as _math
        return input.sum(axis=1) / _math.sqrt(input.shape[1])
    if pt == "first":
        return input[:, 0]
    if pt == "last":
        return input[:, -1]
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(input):
    return input[:, 0]


def sequence_last_step(input):
    return input[:, -1]


def sequence_expand(x, y, ref_level=-1, name=None):
    """Repeat x rows to match y's time dim (padded-batch semantics of the
    legacy LoD expand)."""
    from ..framework.tensor import apply_op
    import jax.numpy as jnp

    def f(a, b):
        reps = b.shape[1] if b.ndim > 1 else 1
        return jnp.repeat(a[:, None], reps, axis=1).reshape(
            (-1,) + a.shape[1:])
    return apply_op(f, x, y, _op_name="sequence_expand")


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None):
    """Temporal conv over [B, T, D] (legacy sequence_conv on padded
    batches): window of filter_size steps -> num_filters."""
    d = input.shape[-1]
    w = create_parameter([filter_size * d, num_filters],
                         dtype=input.dtype.name)
    from ..framework.tensor import apply_op
    import jax.numpy as jnp

    def f(a, k):
        B, T, D = a.shape
        half = (filter_size - 1) // 2
        pad = jnp.pad(a, ((0, 0), (half, filter_size - 1 - half), (0, 0)))
        cols = jnp.stack([pad[:, i:i + T] for i in range(filter_size)],
                         axis=2)  # [B, T, fs, D]
        cols = cols.reshape(B, T, filter_size * D)
        return cols @ k
    out = apply_op(f, input, w, _op_name="sequence_conv")
    if bias_attr is not False:
        b = create_parameter([num_filters], dtype=input.dtype.name,
                             is_bias=True)
        out = out + b
    return _act(out, act)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """PS-backed embedding (static/nn/common.py sparse_embedding): when a
    parameter-server client is initialized (distributed.ps.init_worker),
    rows live on the PS; otherwise a dense embedding parameter."""
    from ..distributed import ps as ps_mod
    cli = ps_mod.get_client()
    if cli is not None:
        emb = ps_mod.DistributedEmbedding(cli, size[1])
        return emb(input)
    return embedding(input, size, padding_idx=padding_idx, dtype=dtype)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=5, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (sampled negatives + BCE),
    the static/nn/common.py nce contract."""
    d = input.shape[-1]
    w = create_parameter([num_total_classes, d], dtype=input.dtype.name)
    b = create_parameter([num_total_classes], dtype=input.dtype.name,
                         is_bias=True)
    from ..framework.tensor import apply_op
    from ..framework import random as rnd
    import jax
    import jax.numpy as jnp
    key = rnd.op_key(input, label)

    def f(x, y, wt, bt, k):
        B = x.shape[0]
        neg = jax.random.randint(k, (B, num_neg_samples), 0,
                                 num_total_classes)
        pos_logit = jnp.sum(x * wt[y.reshape(-1)], axis=-1) + \
            bt[y.reshape(-1)]
        neg_logit = jnp.einsum("bd,bnd->bn", x, wt[neg]) + bt[neg]
        pos_loss = jnp.log1p(jnp.exp(-pos_logit))
        neg_loss = jnp.sum(jnp.log1p(jnp.exp(neg_logit)), axis=-1)
        return (pos_loss + neg_loss)[:, None]
    return apply_op(f, input, label, w, b, key, _op_name="nce")


# -- control flow ---------------------------------------------------------

def cond(pred, true_fn=None, false_fn=None, name=None,
         return_names=None):
    """Static cond: both branches are recorded (they may create ops in
    the program); outputs selected elementwise by ``pred``. This is the
    GSPMD-friendly select form — XLA executes both branches, which is
    the usual TPU tradeoff for tiny branch bodies."""
    t_out = true_fn() if true_fn is not None else None
    if false_fn is None:
        # no else-branch: the reference returns the true branch's output
        # unconditionally in this form
        return t_out
    f_out = false_fn()
    if t_out is None:
        return None
    from ..framework.tensor import apply_op
    import jax.numpy as jnp

    def select(p, a, b):
        return apply_op(
            lambda pp, aa, bb: jnp.where(pp.astype(bool), aa, bb),
            p, a, b, _op_name="cond_select")

    if isinstance(t_out, (list, tuple)):
        return type(t_out)(select(pred, a, b)
                           for a, b in zip(t_out, f_out))
    return select(pred, t_out, f_out)


def case(pred_fn_pairs, default=None, name=None):
    """First matching predicate wins (control_flow.py case); with no
    default, the LAST pair's fn is the fallback (reference contract)."""
    pairs = list(pred_fn_pairs)
    if default is None:
        if not pairs:
            raise ValueError("case needs pred_fn_pairs")
        default = pairs[-1][1]
    out = default()
    for p, fn in reversed(pairs):
        out = cond(p, fn, (lambda o=out: o))
    return out


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Integer-indexed branch select (control_flow.py switch_case)."""
    from ..framework.tensor import apply_op
    import jax.numpy as jnp
    items = list(branch_fns.items()) if isinstance(branch_fns, dict) \
        else list(enumerate(branch_fns))
    if default is None:
        if not items:
            raise ValueError("switch_case needs branch_fns")
        default = items[-1][1]  # reference: last branch is the fallback
    out = default()
    for idx, fn in items:
        eq = apply_op(lambda b, i=int(idx): b.astype(jnp.int32) == i,
                      branch_index, _op_name="switch_eq")
        out = cond(eq, fn, (lambda o=out: o))
    return out


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """Data-dependent loop recorded as ONE op wrapping lax.while_loop;
    the python body runs on tracers through the same eager dispatch
    (gradients through the loop are not supported — matching the
    reference's restriction that while grads need explicit care)."""
    from ..framework.tensor import Tensor, apply_op, no_grad
    import jax

    def f(*arrs):
        def c(vals):
            with no_grad():
                t = [Tensor(v) for v in vals]
                out = cond_fn(*t)
            return out._data.astype(bool).reshape(())

        def b(vals):
            with no_grad():
                t = [Tensor(v) for v in vals]
                out = body_fn(*t)
            out = out if isinstance(out, (list, tuple)) else [out]
            return tuple(o._data for o in out)

        return jax.lax.while_loop(c, b, tuple(arrs))

    res = apply_op(f, *loop_vars, _op_name="while_loop")
    return list(res) if isinstance(res, tuple) else [res]


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """control_flow.py static_pylayer: custom forward with optional
    custom backward (jax.custom_vjp over the recorded op)."""
    from ..framework.tensor import Tensor, apply_op
    import jax

    if backward_fn is None:
        out = forward_fn(*inputs)
        return out

    def fwd_arrays(*arrs):
        t = [Tensor(a) for a in arrs]
        out = forward_fn(*t)
        outs = out if isinstance(out, (list, tuple)) else [out]
        return tuple(o._data for o in outs)

    @jax.custom_vjp
    def op(*arrs):
        return fwd_arrays(*arrs)

    def op_fwd(*arrs):
        return fwd_arrays(*arrs), arrs

    def op_bwd(res, g):
        gt = [Tensor(x) for x in (g if isinstance(g, tuple) else (g,))]
        out = backward_fn(*gt)
        outs = out if isinstance(out, (list, tuple)) else [out]
        return tuple(o._data for o in outs)

    op.defvjp(op_fwd, op_bwd)
    res = apply_op(op, *inputs, _op_name="static_pylayer")
    return res


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host python op via jax.pure_callback (static/nn/common.py py_func);
    ``out`` supplies the result template (shape/dtype)."""
    from ..framework.tensor import apply_op
    import jax
    import numpy as _np
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(o.shape), o.dtype.np_dtype
                                   if hasattr(o.dtype, "np_dtype")
                                   else o.dtype) for o in outs]

    def f(*arrs):
        def host(*np_arrs):
            r = func(*np_arrs)
            rs = r if isinstance(r, (list, tuple)) else [r]
            return tuple(_np.asarray(v) for v in rs)
        res = jax.pure_callback(
            host, tuple(shapes), *arrs, vmap_method="sequential")
        return res if len(shapes) > 1 else res[0]
    return apply_op(f, *xs, _op_name="py_func")
