"""Static-graph core: lazy Variables recorded into a Program.

Reference: python/paddle/base/framework.py (Program :5886, Block :4219,
Variable :1641, Operator :3105) — a protobuf ProgramDesc IR built by every
layer call under ``paddle.enable_static()`` and executed later by the
StandaloneExecutor (paddle/fluid/framework/new_executor/interpretercore.h:30).

TPU-native design: there is no separate op IR to invent — every op in this
framework already funnels through one dispatch point
(``framework.tensor.apply_op``), so static mode simply *defers* that
dispatch.  A ``Variable`` is a data-less Tensor carrying a
``jax.ShapeDtypeStruct`` (with jax.export symbolic dims for None/-1 feed
dims — the InferMeta analog is ``jax.eval_shape``, which reuses the exact
op implementations instead of a second 49k-LoC shape-inference library,
cf. paddle/phi/infermeta/).  Each deferred op appends an ``OpNode`` to the
current ``Program``; ``static.Executor`` replays the node list inside one
``jax.jit`` — XLA is the executor, dependency builder, and memory planner
that interpretercore.h hand-implements for CUDA streams.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.dtype import to_dtype
from ..framework import tensor as tensor_mod
from ..framework.tensor import Tensor

__all__ = [
    "Variable", "OpNode", "Program", "Block", "program_guard",
    "default_main_program", "default_startup_program", "data",
    "in_static_mode", "enable_static_mode", "disable_static_mode",
    "create_parameter", "create_global_var", "append_optimize",
    "append_backward", "gradients", "name_scope",
]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "programs"):
        _tls.programs = []
    return _tls.programs


# process-global default (paddle.enable_static) with a thread-local
# override (program_guard), so a guard in one thread cannot flip e.g. a
# DataLoader worker thread into static mode mid-batch
_static_mode_global = [False]


def in_static_mode() -> bool:
    override = getattr(_tls, "static_override", None)
    if override is not None:
        return override
    return _static_mode_global[0]


def enable_static_mode():
    _static_mode_global[0] = True
    if not hasattr(_tls, "default_main"):
        _tls.default_main = Program()
        _tls.default_startup = Program()


def disable_static_mode():
    _static_mode_global[0] = False


def default_main_program() -> "Program":
    if _stack():
        return _stack()[-1][0]
    if not hasattr(_tls, "default_main"):
        _tls.default_main = Program()
    return _tls.default_main


def default_startup_program() -> "Program":
    if _stack():
        return _stack()[-1][1]
    if not hasattr(_tls, "default_startup"):
        _tls.default_startup = Program()
    return _tls.default_startup


@contextlib.contextmanager
def program_guard(main_program: "Program",
                  startup_program: Optional[
                      "Program"] = None):
    """paddle.static.program_guard analog (thread-local)."""
    prev_override = getattr(_tls, "static_override", None)
    _tls.static_override = True
    _stack().append((main_program,
                     startup_program or Program()))
    try:
        yield
    finally:
        _stack().pop()
        _tls.static_override = prev_override


@contextlib.contextmanager
def name_scope(prefix: str):
    yield


# --------------------------------------------------------------------------
# Variable: a data-less Tensor whose value exists only at Executor.run time
# --------------------------------------------------------------------------

class Variable(Tensor):
    """Symbolic tensor in a Program (base/framework.py:1641 analog)."""

    _is_lazy = True

    def __init__(self, aval: jax.ShapeDtypeStruct, program: "Program",
                 name: Optional[str] = None, producer=None, out_idx: int = 0,
                 is_feed: bool = False, stop_gradient: bool = True):
        # deliberately do NOT call Tensor.__init__ — no data exists
        self._data = None
        self.stop_gradient = stop_gradient
        self.grad = None
        self.grad_node = None
        self._out_idx = out_idx
        self._hooks = {}
        self._retain_grad = False
        self.persistable = False
        self.aval = aval
        self.program = program
        self.producer = producer  # OpNode | None (feed/const source)
        self.is_feed = is_feed
        if name is None:
            program._var_counter += 1
            name = f"_generated_var_{program._var_counter}"
        self.name = name
        program.vars[name] = self

    # -- metadata from the aval -------------------------------------------
    def _shape(self):
        return tuple(d if isinstance(d, int) else -1
                     for d in self.aval.shape)

    @property
    def shape(self):
        return list(self._shape())

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def size(self):
        dims = self._shape()
        if -1 in dims:
            return -1
        return int(np.prod(dims, dtype=np.int64)) if dims else 1

    @property
    def dtype(self):
        return dtype_mod.from_np(np.dtype(self.aval.dtype))

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype.name})")

    def __len__(self):
        d = self._shape()
        if not d:
            raise TypeError("len() of a 0-d Variable")
        if d[0] == -1:
            raise ValueError("first dim of Variable is dynamic")
        return d[0]

    def _no_data(self, what):
        raise RuntimeError(
            f"Variable '{self.name}' has no value at graph-build time; "
            f"{what} is only available from Executor.run fetch results")

    def numpy(self):
        self._no_data("numpy()")

    def item(self):
        self._no_data("item()")

    def __bool__(self):
        self._no_data("bool()")

    def __float__(self):
        self._no_data("float()")

    def __int__(self):
        self._no_data("int()")

    def __array__(self, dtype=None):
        self._no_data("__array__")

    def backward(self, grad_tensor=None, retain_graph=False):
        raise RuntimeError(
            "Variable.backward() is not defined at graph-build time; use "
            "paddle.static.append_backward(loss) or optimizer.minimize")


class OpNode:
    """One deferred op: (fn, inputs, kwargs) -> output Variables.

    The analog of framework.py:3105 Operator, except ``fn`` IS the op
    implementation (a jax-traceable callable), so there is no opcode →
    kernel lookup at execution time.
    """

    __slots__ = ("fn", "inputs", "kwargs", "outputs", "name", "idx")

    def __init__(self, fn, inputs, kwargs, name, idx):
        self.fn = fn
        self.inputs = inputs      # tuple of Variable | Tensor | python const
        self.kwargs = kwargs
        self.outputs: List[Variable] = []
        self.name = name
        self.idx = idx

    @property
    def type(self):
        return self.name

    def __repr__(self):
        ins = [getattr(x, "name", repr(x)) for x in self.inputs]
        outs = [o.name for o in self.outputs]
        return f"Op({self.name}: {ins} -> {outs})"


class Block:
    """Minimal Block shim (framework.py:4219) over the flat op list."""

    def __init__(self, program):
        self.program = program
        self.idx = 0

    @property
    def ops(self):
        return self.program.ops

    @property
    def vars(self):
        return self.program.vars

    def var(self, name):
        return self.program.vars[name]

    def has_var(self, name):
        return name in self.program.vars

    def all_parameters(self):
        return list(self.program._parameters)

    def create_var(self, name=None, shape=None, dtype="float32", **kw):
        aval = _make_aval(shape or [], dtype, self.program)
        return Variable(aval, self.program, name=name)


class Program:
    """Recorded static graph (base/framework.py:5886 analog)."""

    def __init__(self):
        self.ops: List[OpNode] = []
        self.vars: Dict[str, Variable] = {}
        self.random_seed = 0
        self._var_counter = 0
        self._version = 0
        # concrete Tensors captured by ops (parameters and constants): they
        # become jit arguments so in-place updates never retrigger capture
        self._captured: List[Tensor] = []
        self._cap_index: Dict[int, int] = {}
        self._parameters: List[Tensor] = []
        self._opt_specs: List[Tuple[Any, "Variable"]] = []  # (optimizer, loss)
        self._grad_requests: Dict[int, Tuple[Variable, Any]] = {}
        self._feed_order: List[str] = []
        self._sym_scope = None  # jax.export.SymbolicScope, lazily created
        self._rng_feed: Optional["Variable"] = None  # implicit per-run key
        self._rng_counter = 0

    # -- capture ----------------------------------------------------------
    def capture(self, t: Tensor) -> int:
        key = id(t)
        if key not in self._cap_index:
            self._cap_index[key] = len(self._captured)
            self._captured.append(t)
            if not t.stop_gradient or t.persistable:
                self._parameters.append(t)
        return self._cap_index[key]

    def append_op_node(self, fn, inputs, kwargs, name) -> OpNode:
        node = OpNode(fn, inputs, kwargs, name, len(self.ops))
        self.ops.append(node)
        self._version += 1
        return node

    # -- public API --------------------------------------------------------
    def global_block(self) -> Block:
        return Block(self)

    @property
    def blocks(self):
        return [self.global_block()]

    def block(self, idx=0):
        return self.global_block()

    @property
    def num_blocks(self):
        return 1

    def list_vars(self):
        return list(self.vars.values())

    def all_parameters(self):
        return list(self._parameters)

    def parameters(self):
        return list(self._parameters)

    def clone(self, for_test: bool = False) -> "Program":
        """Shallow clone sharing captured tensors (params); for_test drops
        optimizer specs (the reference prunes backward ops)."""
        p = Program.__new__(Program)
        p.__dict__ = {}
        p.ops = list(self.ops)
        p.vars = dict(self.vars)
        p.random_seed = self.random_seed
        p._var_counter = self._var_counter
        p._version = self._version
        p._captured = list(self._captured)
        p._cap_index = dict(self._cap_index)
        p._parameters = list(self._parameters)
        p._opt_specs = [] if for_test else list(self._opt_specs)
        p._grad_requests = dict(self._grad_requests)
        p._feed_order = list(self._feed_order)
        p._sym_scope = self._sym_scope
        p._rng_feed = self._rng_feed
        p._rng_counter = self._rng_counter
        return p

    def __repr__(self):
        lines = [f"Program(ops={len(self.ops)}, vars={len(self.vars)}, "
                 f"params={len(self._parameters)})"]
        lines += [f"  {op!r}" for op in self.ops[:40]]
        if len(self.ops) > 40:
            lines.append(f"  ... (+{len(self.ops) - 40} ops)")
        return "\n".join(lines)

    to_string = __repr__


# --------------------------------------------------------------------------
# The apply_op hook: defer ops touching Variables into the Program
# --------------------------------------------------------------------------

def _make_aval(shape, dtype,
               program: Optional["Program"] = None) -> jax.ShapeDtypeStruct:
    """None/-1 dims become jax.export symbolic dims. Dims are named by
    position within one per-program scope, so the batch dim of every feed
    unifies (x:[d0,4] - y:[d0,1] broadcasts at eval_shape time); genuinely
    unrelated dynamic dims at the same position should be fed concrete."""
    np_dtype = to_dtype(dtype).np_dtype
    dims = []
    for i, d in enumerate(shape):
        if d is None or (isinstance(d, int) and d < 0):
            if program is None:
                program = default_main_program()
            if program._sym_scope is None:
                program._sym_scope = jax.export.SymbolicScope()
            dims.append(jax.export.symbolic_shape(
                f"d{i}", scope=program._sym_scope)[0])
        else:
            dims.append(int(d))
    return jax.ShapeDtypeStruct(tuple(dims), np_dtype)


def target_program(lazy_vars: Sequence["Variable"]) -> "Program":
    """Ops append to the active program_guard program if one is open
    (Paddle semantics; also makes clone() shared-Variable graphs record
    into the clone, not the original), else to the producing program."""
    if _stack():
        program = _stack()[-1][0]
    else:
        program = lazy_vars[0].program
    for v in lazy_vars:
        if v.program is not program and program.vars.get(v.name) is not v:
            raise RuntimeError(
                f"Variable '{v.name}' belongs to a different Program")
    return program


def record_op(fn: Callable, inputs, kwargs, name):
    """Called from apply_op when any input is a Variable."""
    lazy = [x for x in inputs if isinstance(x, Variable)]
    program = target_program(lazy)

    # AMP O1: the eager path casts in apply_op; for deferred ops the cast
    # must replay inside the recorded fn (amp decision baked at build time)
    from ..amp.auto_cast import amp_state, maybe_autocast_inputs
    if amp_state() is not None:
        inner = fn

        def fn(*args, **kw):
            return inner(*maybe_autocast_inputs(name, list(args)), **kw)

    node = program.append_op_node(fn, tuple(inputs), dict(kwargs), name)

    # InferMeta via jax.eval_shape on the SAME op implementation
    traced_pos = [i for i, x in enumerate(inputs) if isinstance(x, Tensor)]
    metas = []
    for i in traced_pos:
        x = inputs[i]
        if isinstance(x, Variable):
            metas.append(x.aval)
        else:
            program.capture(x)
            metas.append(jax.ShapeDtypeStruct(x._data.shape, x._data.dtype))

    def meta_fn(*t_avals):
        full = list(inputs)
        for i, a in zip(traced_pos, t_avals):
            full[i] = a
        return fn(*full, **kwargs)

    out = jax.eval_shape(meta_fn, *metas)

    stop = not (tensor_mod.grad_enabled() and any(
        isinstance(inputs[i], Tensor) and not inputs[i].stop_gradient
        for i in traced_pos))
    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    out_vars = []
    for i, o in enumerate(outs):
        v = Variable(jax.ShapeDtypeStruct(o.shape, o.dtype), program,
                     producer=node, out_idx=i, stop_gradient=stop)
        node.outputs.append(v)
        out_vars.append(v)
    return tuple(out_vars) if multi else out_vars[0]


# register the hook into the eager dispatch funnel
tensor_mod._lazy_cls = Variable
tensor_mod._lazy_record = record_op


# --------------------------------------------------------------------------
# Graph-building user API
# --------------------------------------------------------------------------

def static_rng_key(program: Optional["Program"] = None) -> Variable:
    """A per-op lazy PRNG key: fold_in(run_base_key, build_counter). The
    Executor feeds a fresh base key every run (analog of the reference's
    per-kernel Philox offsets, phi/core/generator.h:32)."""
    if program is None:
        program = default_main_program()
    if program._rng_feed is None:
        k = jax.random.key(0)
        program._rng_feed = Variable(
            jax.ShapeDtypeStruct(k.shape, k.dtype), program,
            name="@rng_base_key@", is_feed=True)
    program._rng_counter += 1
    c = program._rng_counter
    from ..framework.tensor import apply_op
    return apply_op(lambda k: jax.random.fold_in(k, c),
                    program._rng_feed, _op_name="rng_fold_in")


def data(name: str, shape: Sequence[Optional[int]], dtype="float32",
         lod_level: int = 0) -> Variable:
    """paddle.static.data — a feed slot (python/paddle/static/input.py)."""
    program = default_main_program()
    v = Variable(_make_aval(shape, dtype, program), program, name=name,
                 is_feed=True)
    program._feed_order.append(name)
    return v


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None) -> Tensor:
    """Eager parameter registered with the current Program (the analog of
    startup-program initialization: params are concrete from creation)."""
    from ..nn import initializer as init_mod
    from ..framework.tensor import Parameter
    if default_initializer is None:
        default_initializer = (init_mod.Constant(0.0) if is_bias
                               else init_mod.XavierNormal())
    arr = default_initializer(tuple(int(s) for s in shape),
                              to_dtype(dtype).np_dtype)
    p = Parameter(arr, name=name)
    default_main_program().capture(p)
    return p


def create_global_var(shape, value, dtype="float32", persistable=False,
                      name=None) -> Tensor:
    arr = jnp.full(tuple(int(s) for s in shape), value,
                   to_dtype(dtype).np_dtype)
    t = Tensor(arr, name=name)
    t.persistable = persistable
    default_main_program().capture(t)
    return t


def append_optimize(optimizer, loss: Variable):
    """Record optimizer.minimize(loss) into the Program; the Executor
    computes grads inside its jitted replay and applies the (eager)
    optimizer update after each run."""
    if not isinstance(loss, Variable):
        raise TypeError("append_optimize expects a static Variable loss")
    loss.program._opt_specs.append((optimizer, loss))
    loss.program._version += 1


def append_backward(loss: Variable, parameter_list=None,
                    no_grad_set=None) -> List[Tuple[Tensor, Variable]]:
    """paddle.static.append_backward analog: creates fetchable grad
    Variables for every trainable parameter captured by the program."""
    program = loss.program
    if parameter_list is None:
        parameter_list = [p for p in program._parameters
                          if not p.stop_gradient]
    out = []
    for p in parameter_list:
        gv = Variable(
            jax.ShapeDtypeStruct(p._data.shape, p._data.dtype), program,
            name=f"{p.name}@GRAD")
        gv.producer = None
        program._grad_requests[id(gv)] = (loss, p)
        program._version += 1
        out.append((p, gv))
    return out


def gradients(targets, inputs, target_gradients=None,
              no_grad_set=None) -> List[Variable]:
    """paddle.static.gradients analog for params and feed Variables."""
    if isinstance(targets, (list, tuple)):
        if len(targets) != 1:
            raise NotImplementedError("single target supported")
        targets = targets[0]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    program = targets.program
    out = []
    for x in inputs:
        if isinstance(x, Variable) and not x.is_feed:
            raise NotImplementedError(
                "gradients() w.r.t. intermediate Variables is not "
                "supported; fetch grads of feeds or parameters")
        shape = (x.aval.shape if isinstance(x, Variable)
                 else x._data.shape)
        dt = (x.aval.dtype if isinstance(x, Variable) else x._data.dtype)
        gv = Variable(jax.ShapeDtypeStruct(shape, dt), program,
                      name=f"{x.name}@GRAD")
        program._grad_requests[id(gv)] = (targets, x)
        program._version += 1
        out.append(gv)
    return out
