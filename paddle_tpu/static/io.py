"""Static-graph inference model save/load.

Reference: python/paddle/static/io.py save_inference_model /
load_inference_model (.pdmodel/.pdiparams consumed by AnalysisPredictor,
paddle/fluid/inference/api/analysis_predictor.h:105). TPU-native: the
Program's replay function is exported as StableHLO via jax.export —
symbolic feed dims survive export, so one artifact serves any batch size.
File format matches jit.save (``.stablehlo.mlir`` + ``.pdiparams`` +
``.pdmeta``) so ``inference.Predictor`` and ``jit.load`` consume it too.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..framework.io import load as fw_load
from ..framework.io import save as fw_save
from ..framework.tensor import Tensor
from .graph import Program, Variable

__all__ = ["save_inference_model", "load_inference_model",
           "serialize_program", "deserialize_program", "normalize_program"]


def _build_infer_fn(program: Program, feed_vars: List[Variable],
                    fetch_vars: List[Variable]):
    # prune to the subgraph reachable from fetch_vars (the reference's
    # prune pass in static/io.py save_inference_model)
    needed = set()
    stack = [f.producer for f in fetch_vars if f.producer is not None]
    while stack:
        node = stack.pop()
        if node.idx in needed:
            continue
        needed.add(node.idx)
        for x in node.inputs:
            if isinstance(x, Variable) and x.producer is not None:
                stack.append(x.producer)
    live_ops = [op for op in program.ops if op.idx in needed]
    # only captured tensors referenced by live ops get exported
    live_caps = sorted({program._cap_index[id(x)]
                        for op in live_ops for x in op.inputs
                        if isinstance(x, Tensor)
                        and not isinstance(x, Variable)})

    def infer_fn(params_, buffers_, *feeds):
        env: Dict[int, Any] = {}
        for v, val in zip(feed_vars, feeds):
            env[id(v)] = val
        if program._rng_feed is not None:
            # inference artifacts get a fixed key (deterministic serving)
            env[id(program._rng_feed)] = jax.random.key(0)

        def resolve(x):
            if isinstance(x, Variable):
                return env[id(x)]
            if isinstance(x, Tensor):
                return params_[f"cap_{program._cap_index[id(x)]}"]
            return x

        for node in live_ops:
            args = [resolve(x) for x in node.inputs]
            out = node.fn(*args, **node.kwargs)
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            for v, o in zip(node.outputs, outs):
                env[id(v)] = o
        return tuple(env[id(f)] for f in fetch_vars)

    return infer_fn, live_caps


def normalize_program(program, feed_vars, fetch_vars):
    return program


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor,
                         program: Optional[Program] = None, **kwargs):
    """Export the subgraph feed_vars -> fetch_vars as StableHLO."""
    if isinstance(feed_vars, Variable):
        feed_vars = [feed_vars]
    if isinstance(fetch_vars, Variable):
        fetch_vars = [fetch_vars]
    if program is None:
        program = feed_vars[0].program

    infer_fn, live_caps = _build_infer_fn(program, feed_vars, fetch_vars)
    params = {f"cap_{i}": program._captured[i]._data for i in live_caps}
    p_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
               for k, v in params.items()}
    feed_avals = [v.aval for v in feed_vars]  # symbolic dims preserved
    exported = jax.export.export(jax.jit(infer_fn))(
        p_avals, {}, *feed_avals)

    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path_prefix + ".stablehlo.mlir", "wb") as f:
        f.write(exported.serialize())
    fw_save({"params": {k: Tensor(v) for k, v in params.items()},
             "buffers": {}}, path_prefix + ".pdiparams")
    with open(path_prefix + ".pdmeta", "w") as f:
        json.dump({
            "input_specs": [{"shape": v.shape, "dtype": v.dtype.name,
                             "name": v.name} for v in feed_vars],
            "feed_names": [v.name for v in feed_vars],
            "fetch_names": [v.name for v in fetch_vars],
        }, f)


class _LoadedProgram:
    """Runnable handle returned by load_inference_model; Executor.run
    dispatches to it (the reference returns a deserialized ProgramDesc)."""

    def __init__(self, exported, params, feed_names, fetch_names):
        self._exported = exported
        self._params = params
        self.feed_names = feed_names
        self.fetch_names = fetch_names

    def _run(self, feed: Dict[str, Any], fetch_list, return_numpy=True):
        feeds = [np.asarray(feed[n]) for n in self.feed_names]
        outs = self._exported.call(self._params, {}, *feeds)
        outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        if fetch_list:
            sel = []
            for f in fetch_list:
                name = f if isinstance(f, str) else getattr(f, "name", None)
                if name in self.fetch_names:
                    sel.append(outs[self.fetch_names.index(name)])
            if sel:
                outs = sel
        if return_numpy:
            return [np.asarray(jax.device_get(o)) for o in outs]
        return [Tensor(o) for o in outs]


def load_inference_model(path_prefix: str, executor, **kwargs):
    """Returns [program, feed_target_names, fetch_targets] like the
    reference; ``program`` is a _LoadedProgram usable with Executor.run."""
    with open(path_prefix + ".stablehlo.mlir", "rb") as f:
        exported = jax.export.deserialize(f.read())
    state = fw_load(path_prefix + ".pdiparams")
    params = {k: v._data for k, v in state["params"].items()}
    with open(path_prefix + ".pdmeta") as f:
        meta = json.load(f)
    prog = _LoadedProgram(exported, params,
                          meta.get("feed_names", []),
                          meta.get("fetch_names", []))
    return [prog, prog.feed_names, prog.fetch_names]


def serialize_program(feed_vars, fetch_vars, program=None) -> bytes:
    if isinstance(feed_vars, Variable):
        feed_vars = [feed_vars]
    if isinstance(fetch_vars, Variable):
        fetch_vars = [fetch_vars]
    if program is None:
        program = feed_vars[0].program
    infer_fn, live_caps = _build_infer_fn(program, feed_vars, fetch_vars)
    params = {f"cap_{i}": program._captured[i]._data for i in live_caps}
    p_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
               for k, v in params.items()}
    exported = jax.export.export(jax.jit(infer_fn))(
        p_avals, {}, *[v.aval for v in feed_vars])
    return exported.serialize()


def deserialize_program(blob: bytes):
    return jax.export.deserialize(blob)
