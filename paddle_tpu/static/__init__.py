"""paddle.static analog — static-graph build + execution.

Reference: python/paddle/static/ (24.9k LoC: Program/Executor user API,
static.nn, io). TPU-native design in graph.py/executor.py: Variables defer
the framework's single op-dispatch funnel into a recorded Program;
jax.eval_shape is InferMeta; one jax.jit replay is the executor; StableHLO
export is the deployment format.
"""
from ..jit.static_function import InputSpec  # noqa: F401
from .graph import (Program, Variable, program_guard,  # noqa: F401
                    default_main_program, default_startup_program, data,
                    in_static_mode, create_parameter, create_global_var,
                    append_backward, gradients, name_scope)
from .executor import (Executor, CompiledProgram, BuildStrategy,  # noqa
                       ExecutionStrategy, global_scope, scope_guard, Scope,
                       cpu_places, cuda_places, xpu_places, device_guard,
                       save, load, save_to_file, load_from_file,
                       serialize_persistables, deserialize_persistables,
                       load_program_state, set_program_state, accuracy,
                       auc, ctr_metric_bundle, ExponentialMovingAverage,
                       Print, WeightNormParamAttr, IpuStrategy,
                       IpuCompiledProgram, ipu_shard_guard, set_ipu_shard)
from .io import (save_inference_model, load_inference_model,  # noqa: F401
                 serialize_program, deserialize_program, normalize_program)
from . import nn  # noqa: F401


__all__ = [
    "InputSpec", "Program", "Variable", "program_guard",
    "default_main_program", "default_startup_program", "data",
    "in_static_mode", "create_parameter", "create_global_var",
    "append_backward", "gradients", "name_scope",
    "Executor", "CompiledProgram", "BuildStrategy", "ExecutionStrategy",
    "global_scope", "scope_guard", "Scope",
    "save_inference_model", "load_inference_model", "serialize_program",
    "deserialize_program", "normalize_program", "nn",
    "cpu_places", "cuda_places", "xpu_places", "device_guard",
    "save", "load", "save_to_file", "load_from_file",
    "serialize_persistables", "deserialize_persistables",
    "load_program_state", "set_program_state", "accuracy", "auc",
    "ctr_metric_bundle", "ExponentialMovingAverage", "Print",
    "WeightNormParamAttr", "IpuStrategy", "IpuCompiledProgram",
    "ipu_shard_guard", "set_ipu_shard", "py_func",
]
from .nn import py_func  # noqa: F401
