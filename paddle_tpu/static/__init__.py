"""Static-graph compat shims. The framework has no legacy Program IR —
jit.to_static covers graph capture; InputSpec re-exported here for API
compat (reference: python/paddle/static/)."""
from ..jit.static_function import InputSpec  # noqa: F401

__all__ = ["InputSpec"]
