"""Static-graph Executor: jit-compiled Program replay.

Reference: python/paddle/base/executor.py Executor.run feeding a
StandaloneExecutor/PirInterpreter
(paddle/fluid/framework/new_executor/interpretercore.h:30) that builds an
instruction list with stream-aware dependencies and a garbage collector.
TPU-native: the recorded OpNode list is replayed inside ONE ``jax.jit`` —
XLA does scheduling, fusion, and memory planning; the compiled executable
is cached per (program version, feed signature, fetch set), which is the
analog of the reference's executor_cache (base/executor.py _ExecutorCache).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..framework.tensor import Tensor
from .graph import (Program, Variable, default_main_program,
                    default_startup_program)

__all__ = ["Executor", "CompiledProgram", "BuildStrategy",
           "ExecutionStrategy", "global_scope", "scope_guard", "Scope"]


class Scope:
    """Minimal variable-scope shim (paddle/fluid/framework/scope.h:50)."""

    def __init__(self):
        self._vars: Dict[str, Any] = {}

    def var(self, name):
        return self._vars.setdefault(name, _ScopeVar(name))

    def find_var(self, name):
        return self._vars.get(name)

    def drop_kids(self):
        pass


class _ScopeVar:
    def __init__(self, name):
        self.name = name
        self._value = None

    def get_tensor(self):
        return self._value

    def set(self, value, place=None):
        self._value = np.asarray(value)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        global _global_scope
        self._prev = _global_scope
        _global_scope = self.scope
        return self.scope

    def __exit__(self, *exc):
        global _global_scope
        _global_scope = self._prev
        return False


class BuildStrategy:
    def __init__(self):
        self.memory_optimize = None
        self.enable_inplace = None


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1


def CompiledProgram(program, build_strategy=None):
    """Compilation happens in Executor.run via jit; identity here."""
    return program


class Executor:
    """paddle.static.Executor analog."""

    _CACHE_CAP = 64  # compiled-program LRU bound (executor_cache analog)

    def __init__(self, place=None):
        self.place = place
        from collections import OrderedDict
        self._cache: "OrderedDict[Tuple, Any]" = OrderedDict()

    def close(self):
        self._cache.clear()

    # -- internals ---------------------------------------------------------
    def _resolve_fetch(self, program: Program, fetch_list):
        fetch_vars: List[Variable] = []
        for f in fetch_list or []:
            if isinstance(f, str):
                if f not in program.vars:
                    raise KeyError(f"fetch variable '{f}' not in program")
                fetch_vars.append(program.vars[f])
            elif isinstance(f, Variable):
                fetch_vars.append(f)
            elif isinstance(f, Tensor):
                # concrete tensor (e.g. a parameter): fetch current value
                fetch_vars.append(f)
            else:
                raise TypeError(f"bad fetch entry {f!r}")
        return fetch_vars

    def _feed_vars(self, program: Program, feed: Dict[str, Any]):
        unknown = [n for n in feed if n not in program.vars]
        if unknown:
            raise KeyError(
                f"feed entries {unknown} are not variables of this program "
                f"(feeds: {[v.name for v in program.vars.values() if getattr(v, 'is_feed', False)]})")
        # feeding a non-feed Variable overrides its computed value
        # (reference Executor honors feeds of intermediates the same way)
        names = list(feed)
        names.sort(key=lambda n: (program._feed_order.index(n)
                   if n in program._feed_order
                   else len(program._feed_order), n))
        return names

    def _build(self, program: Program, feed_names, fetch_vars, grad_params):
        """Build + jit the replay function.

        Signature: (cap_vals, feed_vals) -> (fetches..., grads...)
        where grads covers program._grad_requests and optimizer params.
        """
        feed_name_set = set(feed_names)
        grad_req = list(program._grad_requests.values())

        # prune to ops reachable from fetches + losses (the analog of the
        # reference's Program.clone(for_test)/prune_backward pruning)
        roots: List[Variable] = []
        for f in fetch_vars:
            if isinstance(f, Variable):
                if id(f) in program._grad_requests:
                    roots.append(program._grad_requests[id(f)][0])
                else:
                    roots.append(f)
        for _, loss_v in program._opt_specs:
            roots.append(loss_v)
        for loss_v, _ in grad_req:
            roots.append(loss_v)
        fed_ids = {id(program.vars[n]) for n in feed_names}
        needed_ops = set()
        stack = [v.producer for v in roots
                 if v.producer is not None and id(v) not in fed_ids]
        while stack:
            node = stack.pop()
            if node.idx in needed_ops:
                continue
            needed_ops.add(node.idx)
            for x in node.inputs:
                if (isinstance(x, Variable) and x.producer is not None
                        and id(x) not in fed_ids):  # fed overrides cut here
                    stack.append(x.producer)
        live_ops = [op for op in program.ops if op.idx in needed_ops]

        def run_graph(cap_vals, feed_vals):
            env: Dict[int, Any] = {}
            for name, val in zip(feed_names, feed_vals):
                env[id(program.vars[name])] = val

            def resolve(x):
                if isinstance(x, Variable):
                    key = id(x)
                    if key not in env:
                        if x.is_feed:
                            raise KeyError(
                                f"feed '{x.name}' missing from feed dict")
                        raise KeyError(
                            f"Variable '{x.name}' used before definition")
                    return env[key]
                if isinstance(x, Tensor):
                    return cap_vals[program._cap_index[id(x)]]
                return x

            for node in live_ops:
                args = [resolve(x) for x in node.inputs]
                out = node.fn(*args, **node.kwargs)
                outs = list(out) if isinstance(out, (tuple, list)) else [out]
                for v, o in zip(node.outputs, outs):
                    if id(v) not in env:  # fed overrides win
                        env[id(v)] = o
            return env

        # which captured tensors need grads (by capture index)
        need_grad_idx: List[int] = []
        grad_feed_names: List[str] = []
        for loss_var, wrt in grad_req:
            if isinstance(wrt, Variable):
                if wrt.name not in feed_name_set:
                    raise KeyError(
                        f"gradient w.r.t. feed '{wrt.name}' requested but "
                        f"it is not fed")
                grad_feed_names.append(wrt.name)
            else:
                need_grad_idx.append(program._cap_index[id(wrt)])
        for opt, loss_var in program._opt_specs:
            for p in opt._parameter_list:
                if not p.stop_gradient and id(p) in program._cap_index:
                    need_grad_idx.append(program._cap_index[id(p)])
        need_grad_idx = sorted(set(need_grad_idx))
        grad_feed_names = sorted(set(grad_feed_names))
        loss_vars = [lv for lv, _ in grad_req] + \
                    [lv for _, lv in program._opt_specs]
        if (need_grad_idx or grad_feed_names) and not loss_vars:
            raise RuntimeError("gradients requested without a loss")
        loss_var = loss_vars[0] if loss_vars else None
        for lv in loss_vars[1:]:
            if lv is not loss_var:
                raise NotImplementedError(
                    "multiple distinct losses in one program")

        def replay(cap_vals, feed_vals):
            grads_by_idx: Dict[int, Any] = {}
            grads_by_feed: Dict[str, Any] = {}
            if loss_var is not None and (need_grad_idx or grad_feed_names):
                # single forward trace: value_and_grad with the whole env
                # as aux, so fetches reuse the same forward computation
                feed_pos = [feed_names.index(n) for n in grad_feed_names]

                def loss_and_env(wrt_caps, wrt_feeds):
                    cv = list(cap_vals)
                    for i, v in zip(need_grad_idx, wrt_caps):
                        cv[i] = v
                    fv = list(feed_vals)
                    for i, v in zip(feed_pos, wrt_feeds):
                        fv[i] = v
                    e = run_graph(cv, fv)
                    return e[id(loss_var)], e

                (_, env), (gc, gf) = jax.value_and_grad(
                    loss_and_env, argnums=(0, 1), has_aux=True)(
                    [cap_vals[i] for i in need_grad_idx],
                    [feed_vals[i] for i in feed_pos])
                grads_by_idx = dict(zip(need_grad_idx, gc))
                grads_by_feed = dict(zip(grad_feed_names, gf))
            else:
                env = run_graph(cap_vals, feed_vals)

            out_fetches = []
            for f in fetch_vars:
                if isinstance(f, Variable):
                    key = id(f)
                    if key in program._grad_requests:
                        _, wrt = program._grad_requests[key]
                        if isinstance(wrt, Variable):
                            out_fetches.append(grads_by_feed[wrt.name])
                        else:
                            out_fetches.append(
                                grads_by_idx[program._cap_index[id(wrt)]])
                    else:
                        out_fetches.append(env[key])
                else:  # concrete Tensor
                    out_fetches.append(cap_vals[program._cap_index[id(f)]])
            opt_grads = [grads_by_idx.get(i) for i in need_grad_idx]
            return out_fetches, opt_grads

        jitted = jax.jit(replay)
        return jitted, need_grad_idx

    # -- public ------------------------------------------------------------
    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None,
            feed_var_name="feed", fetch_var_name="fetch", scope=None,
            return_numpy: bool = True, use_prune: bool = False):
        from .io import _LoadedProgram
        if isinstance(program, _LoadedProgram):
            return program._run(feed or {}, fetch_list, return_numpy)
        if program is None:
            program = default_main_program()
        feed = feed or {}
        fetch_vars = self._resolve_fetch(program, fetch_list)
        if not program.ops:
            # startup program (params are initialized eagerly at creation)
            if fetch_list is None:
                return []
            out = []
            for f in fetch_vars:
                if isinstance(f, Variable):
                    if f.is_feed and f.name in feed:
                        out.append(np.asarray(feed[f.name]))
                    else:
                        raise RuntimeError(
                            f"cannot fetch '{f.name}' from a program with "
                            f"no ops (feed it or add ops)")
                else:
                    out.append(np.asarray(f._data))
            return out

        feed_names = self._feed_vars(program, feed)
        sig = tuple((n, tuple(np.shape(feed[n])),
                     str(np.asarray(feed[n]).dtype)) for n in feed_names)
        feed_vals = [np.asarray(feed[n]) for n in feed_names]
        if program._rng_feed is not None:
            # implicit per-run PRNG base key: fresh randomness each run
            from ..framework import random as rnd
            feed_names = feed_names + [program._rng_feed.name]
            feed_vals = feed_vals + [rnd.next_key()]
        key = (id(program), program._version, sig,
               tuple(id(f) for f in fetch_vars))
        entry = self._cache.get(key)
        if entry is None:
            entry = self._build(program, feed_names, fetch_vars, None)
            self._cache[key] = entry
            if len(self._cache) > self._CACHE_CAP:
                self._cache.popitem(last=False)
        else:
            self._cache.move_to_end(key)
        jitted, need_grad_idx = entry

        cap_vals = [t._data for t in program._captured]
        out_fetches, opt_grads = jitted(cap_vals, feed_vals)

        # apply recorded optimizer updates eagerly (all optimizers/LR
        # schedulers work unmodified; the jitted path is to_static)
        if program._opt_specs and opt_grads:
            grads_by_idx = dict(zip(need_grad_idx, opt_grads))
            for opt, _ in program._opt_specs:
                for p in opt._parameter_list:
                    gi = program._cap_index.get(id(p))
                    if gi is not None and gi in grads_by_idx:
                        p._accumulate_grad(grads_by_idx[gi])
                opt.step()
                opt.clear_grad()

        if fetch_list is None:
            return []
        if return_numpy:
            return [np.asarray(jax.device_get(o)) for o in out_fetches]
        return [Tensor(o) for o in out_fetches]


# ---------------------------------------------------------------------------
# long-tail static parity (python/paddle/static/__init__.py remainder)
# ---------------------------------------------------------------------------

def cpu_places(device_count=None):
    import jax
    n = device_count or 1
    from ..device import CPUPlace
    return [CPUPlace(i) for i in range(n)]


def cuda_places(device_ids=None):
    import jax
    from ..device import TPUPlace
    ids = device_ids if device_ids is not None \
        else range(jax.device_count())
    return [TPUPlace(i) for i in ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


class device_guard:
    """No-op placement context (XLA owns placement)."""

    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def _program_state(program):
    return {t.name: t for t in program._parameters}


def save(program, model_path: str, protocol=4, **configs):
    """Persist a Program's parameters (static/io.py save)."""
    from ..framework.io import save as fw_save
    fw_save(_program_state(program), model_path + ".pdparams"
            if not model_path.endswith(".pdparams") else model_path)


def load(program, model_path: str, executor=None, var_list=None):
    from ..framework.io import load as fw_load
    path = model_path if model_path.endswith(".pdparams") \
        else model_path + ".pdparams"
    state = fw_load(path)
    set_program_state(program, state)


def save_to_file(path: str, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def serialize_persistables(feed_vars, fetch_vars, program=None) -> bytes:
    import pickle
    if program is None:
        from .graph import default_main_program
        program = default_main_program()
    return pickle.dumps({k: np.asarray(v.numpy())
                         for k, v in _program_state(program).items()})


def deserialize_persistables(program, data: bytes, executor=None):
    import pickle
    set_program_state(program, pickle.loads(data))


def load_program_state(model_path: str, var_list=None):
    from ..framework.io import load as fw_load
    path = model_path if model_path.endswith(".pdparams") \
        else model_path + ".pdparams"
    return fw_load(path)


def set_program_state(program, state_dict):
    from ..framework.tensor import Tensor, no_grad
    by_name = _program_state(program)
    with no_grad():
        for k, v in state_dict.items():
            if k in by_name:
                arr = v._data if isinstance(v, Tensor) else v
                import jax.numpy as jnp
                by_name[k]._data = jnp.asarray(
                    arr, by_name[k]._data.dtype)


def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy as a recorded op (static/nn metric)."""
    from ..framework.tensor import apply_op
    import jax.numpy as jnp

    def f(x, y):
        topk = jnp.argsort(-x, axis=-1)[..., :k]
        hit = jnp.any(topk == y.reshape(-1, 1), axis=-1)
        return jnp.mean(hit.astype(jnp.float32))
    return apply_op(f, input, label, _op_name="accuracy")


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Batch AUC over the positive-class score (approximate, recorded)."""
    from ..framework.tensor import apply_op
    import jax.numpy as jnp

    def f(x, y):
        score = x[:, 1] if x.ndim == 2 and x.shape[1] >= 2 else \
            x.reshape(-1)
        yf = y.reshape(-1).astype(jnp.float32)
        order = jnp.argsort(score)
        ranks = jnp.empty_like(order).at[order].set(
            jnp.arange(1, score.shape[0] + 1))
        n_pos = jnp.sum(yf)
        n_neg = yf.shape[0] - n_pos
        sum_rank_pos = jnp.sum(ranks * yf)
        auc_v = (sum_rank_pos - n_pos * (n_pos + 1) / 2) / \
            jnp.maximum(n_pos * n_neg, 1.0)
        return auc_v.astype(jnp.float32)
    a = apply_op(f, input, label, _op_name="auc")
    return a, a, [a]


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """CTR metrics (abs err, sqr err, q, pos, total) as recorded ops."""
    from ..framework.tensor import apply_op
    import jax.numpy as jnp

    def f(x, y):
        s = x.reshape(-1)
        yf = y.reshape(-1).astype(jnp.float32)
        abserr = jnp.sum(jnp.abs(s - yf))
        sqrerr = jnp.sum((s - yf) ** 2)
        q = jnp.sum(s)
        pos = jnp.sum(yf)
        total = jnp.asarray(s.shape[0], jnp.float32)
        return abserr, sqrerr, q, pos, total
    return apply_op(f, input, label, _op_name="ctr_metric_bundle")


class ExponentialMovingAverage:
    """EMA of parameters (static/ema.py): update() after each step,
    apply()/restore() around evaluation."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._ema = {}
        self._backup = {}
        self._step = 0

    def update(self, program=None):
        from .graph import default_main_program
        program = program or default_main_program()
        self._step += 1
        for p in program._parameters:
            if p.stop_gradient:
                continue
            prev = self._ema.get(p.name)
            cur = p._data.astype("float32")
            # zero-init + bias correction in apply() (paddle ema.py)
            if prev is None:
                prev = cur * 0
            self._ema[p.name] = \
                self._decay * prev + (1 - self._decay) * cur

    def apply(self, executor=None, need_restore=True):
        """Context manager: installs EMA weights, restores on exit when
        need_restore (reference static/nn/common.py contract)."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            from .graph import default_main_program
            program = default_main_program()
            for p in program._parameters:
                if p.name in self._ema:
                    self._backup[p.name] = p._data
                    # bias-corrected EMA (decay correction, zero-init)
                    corr = 1 - self._decay ** max(self._step, 1)
                    p._data = (self._ema[p.name] / corr).astype(
                        p._data.dtype)
            try:
                yield self
            finally:
                if need_restore:
                    self.restore(executor)
        return _ctx()

    def restore(self, executor=None):
        from .graph import default_main_program
        program = default_main_program()
        for p in program._parameters:
            if p.name in self._backup:
                p._data = self._backup.pop(p.name)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug print inside the compiled program (control_flow.py Print)
    via jax.debug.print; returns the input unchanged."""
    from ..framework.tensor import apply_op
    import jax

    # user text must not be interpreted as a format template
    msg = (message or "").replace("{", "{{").replace("}", "}}")

    def f(a):
        jax.debug.print(msg + " {x}", x=a)
        return a
    return apply_op(f, input, _op_name="print")


class WeightNormParamAttr:
    """ParamAttr marker requesting weight normalization
    (static/param_attr.py); consumed by layers that support it."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


class IpuStrategy:
    """IPU stubs: not a supported backend (TPU-native build)."""

    def __init__(self):
        raise NotImplementedError("IPU is not supported on this build")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU is not supported on this build")


def ipu_shard_guard(index=-1, stage=-1):
    raise NotImplementedError("IPU is not supported on this build")


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise NotImplementedError("IPU is not supported on this build")
