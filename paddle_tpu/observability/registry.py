"""Framework-wide metrics registry.

Reference shape: the CUPTI/host tracer stack threads RecordEvent
annotations through every layer but keeps no queryable aggregate state
— each subsystem here grew its own (profiler event list, serving
EngineMetrics, jit module globals). This registry is the one substrate
they all publish through: ``Counter`` / ``Gauge`` / ``Histogram``
families with label sets, a process-global default registry, and two
exporters — Prometheus text exposition (``to_prometheus``) for
scraping/snapshot files and a JSON tree (``to_json``) for programmatic
assertions.

Design constraints that shaped it:

- **Thread-safe**: dataloader workers, the watchdog thread, and the
  serving host loop all publish concurrently; one registry ``RLock``
  guards family creation, each child instrument carries its own lock
  for value updates (no global contention on the hot increment path).
- **Injectable clock** (``time_fn``): snapshots carry a timestamp, and
  tests/benchmarks drive it on a virtual timeline — no sleeps.
- **Cardinality guard**: a label set is an allocation that lives
  forever; ``max_label_sets`` (per family) turns an unbounded-label
  bug (e.g. a request id used as a label) into an immediate
  ``MetricError`` instead of a slow leak.
- **Full metric names** are explicit (``ptpu_<layer>_<name>_<unit>``,
  see docs/OBSERVABILITY.md for the convention) — no hidden prefixing.
"""
from __future__ import annotations

import json
import math
import re
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["MetricError", "Counter", "Gauge", "Histogram",
           "MetricRegistry", "default_registry"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# prometheus client default buckets: sub-ms host events up to
# multi-second step/queue waits
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class MetricError(ValueError):
    """Registration conflict, bad name/label, or cardinality overflow."""


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if math.isnan(f):
        return "NaN"       # valid exposition-format sample value
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class _Child:
    """One (label values) cell of a family; owns its own lock."""

    def __init__(self, family: "_Family", labels: Tuple[str, ...]):
        self._family = family
        self._labels = labels
        self._lock = threading.Lock()


class _CounterChild(_Child):
    def __init__(self, family, labels):
        super().__init__(family, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise MetricError(
                f"counter {self._family.name} cannot decrease (inc {n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self):
        with self._lock:
            self._value = 0.0


class _GaugeChild(_Child):
    def __init__(self, family, labels):
        super().__init__(family, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self):
        with self._lock:
            self._value = 0.0


class _HistogramChild(_Child):
    def __init__(self, family, labels):
        super().__init__(family, labels)
        self._bucket_counts = [0] * len(family.buckets)
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._sum += v
            self._count += 1
            # per-bucket storage (first bucket that fits); exporters
            # cumulate on the way out, as the exposition format needs.
            # NaN compares False against every bound, which would
            # desync _count from the bucket sums — park it in +Inf.
            if math.isnan(v):
                self._bucket_counts[-1] += 1
                return
            for i, ub in enumerate(self._family.buckets):
                if v <= ub:
                    self._bucket_counts[i] += 1
                    break

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float:
        """Estimate the q-th percentile (0..100) by linear
        interpolation inside the owning bucket (Prometheus
        ``histogram_quantile`` semantics; exact tails live in
        EngineMetrics which keeps raw samples)."""
        with self._lock:
            counts = list(self._bucket_counts)
            total = self._count
        if not total:
            return 0.0
        target = (q / 100.0) * total
        cum = 0
        lo = 0.0
        ubs = self._family.buckets
        for i, ub in enumerate(ubs):
            prev = cum
            cum += counts[i]
            if cum >= target:
                if ub == math.inf:
                    return lo          # open tail: best effort
                frac = ((target - prev) / counts[i]) if counts[i] else 0.0
                return lo + (ub - lo) * frac
            lo = ub if ub != math.inf else lo
        return lo

    def quantile(self, q: float) -> float:
        """Estimate the q-th quantile (0..1) — the Prometheus
        ``histogram_quantile`` convention. Linear interpolation
        between bucket bounds: the owning bucket ``(lo, ub]`` is
        found by cumulative count, then the estimate is ``lo + (ub -
        lo) * frac`` where ``frac`` is the target's fractional
        position among the bucket's own observations (uniform-within-
        bucket assumption). The open +Inf tail returns its lower
        bound; an empty histogram returns 0.0."""
        if not 0.0 <= q <= 1.0:
            raise MetricError(f"quantile takes q in [0, 1], got {q}")
        return self.percentile(q * 100.0)

    def _reset(self):
        with self._lock:
            self._bucket_counts = [0] * len(self._family.buckets)
            self._sum = 0.0
            self._count = 0


class _Family:
    """A named metric with a fixed label schema; children per label
    set. Families with no labels proxy the instrument API straight to
    their single anonymous child."""

    kind = ""
    _child_cls = _Child

    def __init__(self, registry: "MetricRegistry", name: str,
                 description: str, label_names: Tuple[str, ...],
                 max_label_sets: int):
        self._registry = registry
        self.name = name
        self.description = description
        self.label_names = label_names
        self.max_label_sets = max_label_sets
        self._children: Dict[Tuple[str, ...], _Child] = {}
        self._lock = threading.Lock()
        if not label_names:
            self._children[()] = self._child_cls(self, ())

    def labels(self, **kv) -> _Child:
        if set(kv) != set(self.label_names):
            raise MetricError(
                f"{self.name} takes labels {self.label_names}, "
                f"got {tuple(sorted(kv))}")
        key = tuple(str(kv[n]) for n in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= self.max_label_sets:
                    raise MetricError(
                        f"{self.name}: label cardinality guard — "
                        f"{len(self._children)} label sets already "
                        f"registered (max_label_sets="
                        f"{self.max_label_sets}); a high-cardinality "
                        f"value (request id? timestamp?) is being used "
                        f"as a label")
                child = self._child_cls(self, key)
                self._children[key] = child
            return child

    def _default_child(self) -> _Child:
        if self.label_names:
            raise MetricError(
                f"{self.name} declares labels {self.label_names}; "
                f"use .labels(...)")
        return self._children[()]

    def _sorted_children(self) -> List[_Child]:
        with self._lock:
            return [self._children[k] for k in sorted(self._children)]

    def reset(self) -> None:
        """Zero every child (label sets are kept — a reset must not
        un-register schemas tests or dashboards rely on)."""
        with self._lock:
            for c in self._children.values():
                c._reset()


class Counter(_Family):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, n: float = 1.0) -> None:
        self._default_child().inc(n)

    @property
    def value(self) -> float:
        return self._default_child().value


class Gauge(_Family):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, v: float) -> None:
        self._default_child().set(v)

    def inc(self, n: float = 1.0) -> None:
        self._default_child().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self._default_child().dec(n)

    @property
    def value(self) -> float:
        return self._default_child().value


def _normalize_buckets(buckets: Optional[Sequence[float]]):
    bs = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
    if bs and bs[-1] != math.inf:
        bs = bs + (math.inf,)
    return bs


class Histogram(_Family):
    kind = "histogram"
    _child_cls = _HistogramChild

    def __init__(self, registry, name, description, label_names,
                 max_label_sets, buckets: Optional[Sequence[float]] = None):
        bs = _normalize_buckets(buckets)
        if not bs:
            raise MetricError(f"{name}: empty bucket list")
        self.buckets = bs
        super().__init__(registry, name, description, label_names,
                         max_label_sets)

    def observe(self, v: float) -> None:
        self._default_child().observe(v)

    @property
    def count(self) -> int:
        return self._default_child().count

    @property
    def sum(self) -> float:
        return self._default_child().sum

    def percentile(self, q: float) -> float:
        return self._default_child().percentile(q)

    def quantile(self, q: float) -> float:
        """q in 0..1 (see :meth:`_HistogramChild.quantile`)."""
        return self._default_child().quantile(q)


class MetricRegistry:
    """Create-or-get metric families; export the whole set atomically.

    ``time_fn`` stamps snapshots (injectable for virtual-clock tests);
    ``max_label_sets`` is the per-family cardinality ceiling.
    """

    def __init__(self, time_fn=time.time, max_label_sets: int = 64):
        self.now = time_fn
        self.max_label_sets = int(max_label_sets)
        self._families: Dict[str, _Family] = {}
        self._lock = threading.RLock()

    # -- family factories ----------------------------------------------
    def _get_or_create(self, cls, name, description, labels, **kw):
        if not _NAME_RE.match(name or ""):
            raise MetricError(f"invalid metric name {name!r}")
        labels = tuple(labels)
        for ln in labels:
            if not _LABEL_RE.match(ln):
                raise MetricError(f"invalid label name {ln!r}")
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if type(fam) is not cls or fam.label_names != labels:
                    raise MetricError(
                        f"metric {name} already registered as "
                        f"{fam.kind}{fam.label_names}, requested "
                        f"{cls.kind}{labels}")
                want = kw.get("buckets")
                if want is not None and \
                        _normalize_buckets(want) != fam.buckets:
                    # buckets are part of the schema too: silently
                    # returning the other schema would misplace every
                    # observation
                    raise MetricError(
                        f"histogram {name} already registered with "
                        f"buckets {fam.buckets}, requested "
                        f"{_normalize_buckets(want)}")
                return fam
            fam = cls(self, name, description, labels,
                      self.max_label_sets, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, description: str = "",
                labels: Iterable[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, description, labels)

    def gauge(self, name: str, description: str = "",
              labels: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, description, labels)

    def histogram(self, name: str, description: str = "",
                  labels: Iterable[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, description, labels,
                                   buckets=buckets)

    # -- introspection -------------------------------------------------
    def families(self) -> List[str]:
        with self._lock:
            return sorted(self._families)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def reset(self) -> None:
        """Zero every value; families and label sets survive (handles
        held by instrumented modules keep working)."""
        with self._lock:
            fams = list(self._families.values())
        for f in fams:
            f.reset()

    # -- exporters -----------------------------------------------------
    def to_json(self) -> dict:
        out = {"ts": float(self.now()), "metrics": {}}
        with self._lock:
            fams = [self._families[n] for n in sorted(self._families)]
        for fam in fams:
            rows = []
            for c in fam._sorted_children():
                row = {"labels": dict(zip(fam.label_names, c._labels))}
                if fam.kind == "histogram":
                    with c._lock:
                        counts = list(c._bucket_counts)
                        s, n = c._sum, c._count
                    cum, buckets = 0, {}
                    for ub, bc in zip(fam.buckets, counts):
                        cum += bc
                        buckets[_fmt(ub)] = cum
                    row["buckets"] = buckets   # cumulative, le-keyed
                    row["sum"] = s
                    row["count"] = n
                else:
                    row["value"] = c.value
                rows.append(row)
            out["metrics"][fam.name] = {
                "type": fam.kind, "help": fam.description,
                "label_names": list(fam.label_names), "samples": rows}
        return out

    def to_json_str(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.to_json(), indent=indent)

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            fams = [self._families[n] for n in sorted(self._families)]

        def lbl(names, values, extra=()):
            pairs = [f'{n}="{_escape_label(v)}"'
                     for n, v in zip(names, values)] + list(extra)
            return "{" + ",".join(pairs) + "}" if pairs else ""

        for fam in fams:
            if fam.description:
                h = fam.description.replace("\\", r"\\") \
                    .replace("\n", r"\n")
                lines.append(f"# HELP {fam.name} {h}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            if fam.kind == "histogram" and not fam._sorted_children():
                # a labeled histogram family nobody has observed yet
                # still exposes its _count/_sum (and the +Inf bucket
                # the pair implies): dashboards and the watchtower
                # read "registered but zero traffic" instead of
                # "family missing", and rate() starts from 0 rather
                # than a gap
                lines.append(f'{fam.name}_bucket{{le="+Inf"}} 0')
                lines.append(f"{fam.name}_sum 0")
                lines.append(f"{fam.name}_count 0")
            for c in fam._sorted_children():
                ls = lbl(fam.label_names, c._labels)
                if fam.kind == "histogram":
                    with c._lock:
                        counts = list(c._bucket_counts)
                        s, n = c._sum, c._count
                    cum = 0
                    for ub, bc in zip(fam.buckets, counts):
                        cum += bc
                        bl = lbl(fam.label_names, c._labels,
                                 [f'le="{_fmt(ub)}"'])
                        lines.append(
                            f"{fam.name}_bucket{bl} {cum}")
                    lines.append(f"{fam.name}_sum{ls} {_fmt(s)}")
                    lines.append(f"{fam.name}_count{ls} {n}")
                else:
                    lines.append(f"{fam.name}{ls} {_fmt(c.value)}")
        return "\n".join(lines) + "\n"


_default = MetricRegistry()


def default_registry() -> MetricRegistry:
    """The process-global registry every built-in layer publishes to."""
    return _default
