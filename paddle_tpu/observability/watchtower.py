"""Watchtower: the sensing layer over the telemetry plane.

PR 11 built the *emission* half of cluster observability — per-request
SLO attribution, merged Prometheus exposition, clock-aligned traces —
but nothing consumed those signals. Watchtower is the consumer every
future controller (adaptive chunk budgets, prefix-affinity routing,
replica autoscaling) trusts before acting:

- **Multi-window SLO burn rates.** Each :class:`SLOObjective` declares
  a latency threshold and a good-event target over a phase stream —
  either a registry *histogram* family (TTFT, queue wait, step time,
  promotion wait) or a per-request *attribution* phase from
  :meth:`ClusterTelemetry.slo_attribution` (``queue``, ``dispatch``,
  ``prefill``, ``decode``, ``handoff``, ``failover``,
  ``kv_promotion``). Burn rate = (observed bad fraction) / (error
  budget); an incident requires BOTH the fast window (default 30 s)
  and the slow window (default 5 m) to exceed their thresholds — the
  classic multi-window multi-burn-rate rule, which pages on real
  budget fires but not on single stragglers. All windows run on the
  injectable clock (``time_fn``), so tests and the chaos band drive
  them on virtual timelines.

- **Anomaly detectors.** Each stream (step latency, queue depth,
  promotion wait, recompile count) feeds an EWMA detector (smoothed
  mean/variance) AND a robust z-score detector (median/MAD over a
  rolling window — immune to the very outliers it hunts); a sample
  must trip *both* to raise an incident, which suppresses the false
  positives either one alone produces on cold streams. Monotonic
  progress is watched separately: an engine with queued or active
  work whose step counter stops advancing is **stalled**, a request
  the metrics plane tracks that the engine no longer knows is
  **orphaned** (conservation broken upstream of the ledger audit),
  and a worker whose scraped snapshot age exceeds the heartbeat bound
  is **silent**.

- **Structured incidents.** A trip emits an :class:`Incident` carrying
  the dominant-phase attribution (computed from the per-phase
  breakdown of recent attribution records), the offending request
  ids, a flight-recorder ring snapshot, and a trace excerpt — deduped
  by a stable fingerprint (kind + phase + source key), counted in
  ``ptpu_incidents_total{kind,phase}``, and served from the front
  door's ``/healthz`` + ``/incidents`` endpoints.
  ``tools/ptpu_doctor.py`` renders the same snapshot as a human
  diagnosis.

Hot-path contract (micro-asserted in tests/test_watchtower.py the
same way ``maybe_fail``'s disarmed path is): ``observe_step()`` is ONE
counter increment — no lock, no clock read, no allocation — and
``poll()`` between window boundaries is one clock read + compare. All
stream reading and statistics happen at window boundaries only, out of
band of token emission.
"""
from __future__ import annotations

import hashlib
import math
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["SLOObjective", "DEFAULT_OBJECTIVES", "Incident",
           "EwmaDetector", "RobustZDetector", "Watchtower",
           "render_diagnosis"]

# the closed phase vocabulary incidents attribute to (bounded: these
# are Prometheus label values on ptpu_incidents_total)
PHASES = ("queue", "dispatch", "prefill", "decode", "handoff",
          "failover", "kv_promotion", "compile")

# slo_attribution() record key -> incident phase (chunked prefill
# bills to prefill, failover replay to failover)
_ATTR_PHASE_KEYS = (("queue_s", "queue"),
                    ("dispatch_rpc_s", "dispatch"),
                    ("prefill_s", "prefill"),
                    ("chunked_prefill_s", "prefill"),
                    ("decode_s", "decode"),
                    ("handoff_s", "handoff"),
                    ("kv_promotion_s", "kv_promotion"),
                    ("failover_replay_s", "failover"))


@dataclass(frozen=True)
class SLOObjective:
    """One declared objective: "``objective`` of events finish the
    phase within ``threshold_s``". Exactly one source:

    - ``family``: a registry histogram family name. ``threshold_s``
      snaps UP to the nearest bucket bound (cumulative bucket counts
      are the only resolution a histogram has), so pick thresholds on
      bucket edges for exact accounting.
    - ``phase``: an attribution phase name (``queue`` …
      ``kv_promotion``); events are per-request records from
      ``ClusterTelemetry.slo_attribution()``.
    """
    name: str
    threshold_s: float
    objective: float = 0.99          # target good fraction
    family: Optional[str] = None     # histogram source
    phase: Optional[str] = None      # attribution source (and/or the
    #                                  phase burn incidents carry)
    fast_window_s: float = 30.0
    slow_window_s: float = 300.0
    fast_burn: float = 14.0          # burn-rate trip thresholds
    slow_burn: float = 6.0
    min_events: int = 5              # fast-window event floor

    def __post_init__(self):
        if self.family is None and self.phase is None:
            raise ValueError(
                f"objective {self.name!r} needs a histogram family "
                f"or an attribution phase")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective {self.name!r}: target fraction must be "
                f"in (0, 1), got {self.objective}")


# sane real-clock defaults for a live front door; the chaos band and
# tests declare their own (virtual-second) objectives
DEFAULT_OBJECTIVES: Tuple[SLOObjective, ...] = (
    SLOObjective("ttft_p99", threshold_s=2.5, objective=0.99,
                 family="ptpu_serving_ttft_seconds", phase="queue"),
    SLOObjective("queue_wait_p95", threshold_s=1.0, objective=0.95,
                 family="ptpu_serving_queue_wait_seconds",
                 phase="queue"),
    SLOObjective("step_p99", threshold_s=1.0, objective=0.99,
                 family="ptpu_serving_step_seconds", phase="decode"),
    SLOObjective("promotion_wait_p95", threshold_s=2.5,
                 objective=0.95,
                 family="ptpu_kv_promotion_wait_seconds",
                 phase="kv_promotion"),
)


class EwmaDetector:
    """Exponentially weighted mean/variance; trips when a sample
    deviates from the smoothed mean by more than ``k`` smoothed
    standard deviations (with a relative floor so near-constant
    streams don't trip on noise). Warmup samples never trip."""

    def __init__(self, alpha: float = 0.3, k: float = 6.0,
                 warmup: int = 8):
        self.alpha = float(alpha)
        self.k = float(k)
        self.warmup = int(warmup)
        self.mean: Optional[float] = None
        self.var = 0.0
        self.n = 0

    def update(self, x: float) -> bool:
        x = float(x)
        trip = False
        if self.n >= self.warmup and self.mean is not None:
            scale = max(math.sqrt(max(self.var, 0.0)),
                        0.1 * abs(self.mean), 1e-9)
            trip = abs(x - self.mean) > self.k * scale
        if self.mean is None:
            self.mean = x
        else:
            d = x - self.mean
            self.mean += self.alpha * d
            # West's EWMA variance update
            self.var = (1.0 - self.alpha) * (self.var
                                             + self.alpha * d * d)
        self.n += 1
        return trip


class RobustZDetector:
    """Median/MAD z-score over a rolling window. MAD is scaled by
    1.4826 (consistency with the normal sigma) and floored at 5% of
    |median| so an exactly-constant stream (virtual clocks produce
    these) doesn't divide by zero and page on the first wobble."""

    def __init__(self, window: int = 64, z: float = 8.0,
                 min_samples: int = 8):
        self.z = float(z)
        self.min_samples = int(min_samples)
        self.samples: deque = deque(maxlen=int(window))

    @staticmethod
    def _median(xs: List[float]) -> float:
        s = sorted(xs)
        n = len(s)
        mid = n // 2
        return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])

    def update(self, x: float) -> bool:
        x = float(x)
        trip = False
        if len(self.samples) >= self.min_samples:
            xs = list(self.samples)
            med = self._median(xs)
            mad = self._median([abs(v - med) for v in xs])
            scale = max(1.4826 * mad, 0.05 * abs(med), 1e-9)
            trip = abs(x - med) / scale > self.z
        self.samples.append(x)
        return trip


@dataclass
class Incident:
    """One tripped detector, deduped by ``fingerprint``. ``detail``
    carries the detector-specific evidence (burn rates, per-phase
    breakdown, death reasons); ``flight`` and ``trace`` are bounded
    excerpts captured AT trip time."""
    kind: str                 # slo_burn | anomaly | stall |
    #                           request_orphaned | worker_death |
    #                           partition
    phase: str                # dominant-phase attribution (PHASES)
    summary: str
    ts: float
    fingerprint: str
    detail: Dict[str, Any] = field(default_factory=dict)
    request_ids: Tuple[int, ...] = ()
    flight: Tuple[dict, ...] = ()
    trace: Tuple[dict, ...] = ()
    count: int = 1            # dedup hits within the window
    last_ts: float = 0.0

    def to_json(self) -> dict:
        return {"kind": self.kind, "phase": self.phase,
                "summary": self.summary, "ts": self.ts,
                "last_ts": self.last_ts, "count": self.count,
                "fingerprint": self.fingerprint,
                "detail": dict(self.detail),
                "request_ids": list(self.request_ids),
                "flight": [dict(r) for r in self.flight],
                "trace": [dict(r) for r in self.trace]}


def _fingerprint(kind: str, phase: str, key: str) -> str:
    h = hashlib.sha1(f"{kind}|{phase}|{key}".encode()).hexdigest()
    return h[:16]


class _MetricView:
    """Read adapter over one ``MetricRegistry.to_json()`` snapshot."""

    def __init__(self, snap: dict):
        self._m = (snap or {}).get("metrics") or {}

    def counter_total(self, name: str) -> float:
        fam = self._m.get(name)
        if not fam or fam.get("type") != "counter":
            return 0.0
        return float(sum(float(s.get("value", 0.0))
                         for s in fam.get("samples", ())))

    def counter_by_label(self, name: str, label: str
                         ) -> Dict[str, float]:
        fam = self._m.get(name)
        out: Dict[str, float] = {}
        if not fam or fam.get("type") != "counter":
            return out
        for s in fam.get("samples", ()):
            lv = str((s.get("labels") or {}).get(label, ""))
            out[lv] = out.get(lv, 0.0) + float(s.get("value", 0.0))
        return out

    def gauge(self, name: str) -> Optional[float]:
        fam = self._m.get(name)
        if not fam or fam.get("type") != "gauge":
            return None
        samples = fam.get("samples", ())
        if not samples:
            return None
        return float(sum(float(s.get("value", 0.0))
                         for s in samples))

    def hist(self, name: str) -> Optional[dict]:
        """Aggregate histogram across label sets: cumulative buckets
        (le-string keyed), sum, count — or None if absent/empty."""
        fam = self._m.get(name)
        if not fam or fam.get("type") != "histogram":
            return None
        buckets: Dict[str, int] = {}
        total_s, total_n = 0.0, 0
        for s in fam.get("samples", ()):
            for le, c in (s.get("buckets") or {}).items():
                buckets[le] = buckets.get(le, 0) + int(c)
            total_s += float(s.get("sum", 0.0))
            total_n += int(s.get("count", 0))
        if not buckets and not total_n:
            return None
        return {"buckets": buckets, "sum": total_s, "count": total_n}


def _le_key(le: str) -> float:
    return math.inf if le == "+Inf" else float(le)


def _good_count(hist: dict, threshold: float) -> int:
    """Cumulative count at the smallest bucket bound >= threshold
    (the threshold snaps UP to bucket resolution)."""
    best_le, best_cum = None, 0
    for le, cum in hist["buckets"].items():
        b = _le_key(le)
        if b >= threshold and (best_le is None or b < best_le):
            best_le, best_cum = b, int(cum)
    return best_cum if best_le is not None else int(hist["count"])


class _BurnState:
    """Per-objective windowed good/bad accounting: a ring of
    ``(t, events, bad)`` deltas appended once per evaluation, pruned
    past the slow window."""

    def __init__(self, obj: SLOObjective):
        self.obj = obj
        self.ring: deque = deque()
        self.prev_total: Optional[int] = None
        self.prev_bad = 0
        self.seen_rids: "OrderedDict[int, bool]" = OrderedDict()

    def push(self, now: float, d_total: int, d_bad: int) -> None:
        if d_total or d_bad:
            self.ring.append((now, int(d_total), int(d_bad)))
        horizon = now - self.obj.slow_window_s
        while self.ring and self.ring[0][0] < horizon:
            self.ring.popleft()

    def window(self, now: float, w: float) -> Tuple[int, int]:
        t0 = now - w
        total = bad = 0
        for t, d, b in self.ring:
            if t >= t0:
                total += d
                bad += b
        return total, bad

    def burn(self, now: float, w: float) -> float:
        total, bad = self.window(now, w)
        if total <= 0:
            return 0.0
        frac = min(1.0, bad / total)
        return frac / max(1e-9, 1.0 - self.obj.objective)


class Watchtower:
    """The streaming health engine. Construct one per registry you
    want watched; attach sources, then drive it:

    - ``observe_step()`` from the engine hot path (one counter bump);
    - ``poll()`` from any serving loop (front-door pump, chaos loop,
      supervisor poll) — evaluates only when ``eval_interval_s`` has
      elapsed on the injected clock;
    - ``flush()`` to force an evaluation (shutdown, tests).

    The first evaluation only primes counter baselines (a watchtower
    attached to a long-lived registry must not page on history)."""

    def __init__(self, *,
                 registry,
                 objectives: Tuple[SLOObjective, ...] =
                 DEFAULT_OBJECTIVES,
                 telemetry=None,
                 time_fn: Callable[[], float] = time.monotonic,
                 eval_interval_s: float = 5.0,
                 dedup_window_s: float = 300.0,
                 max_incidents: int = 128,
                 stall_after_s: Optional[float] = 60.0,
                 heartbeat_max_age_s: Optional[float] = None,
                 anomaly_streams: bool = True,
                 ewma_alpha: float = 0.3, ewma_k: float = 6.0,
                 z_threshold: float = 8.0, min_samples: int = 8,
                 trace_excerpt: int = 32, flight_excerpt: int = 16):
        self.registry = registry
        self.telemetry = telemetry
        self.now = time_fn
        self.eval_interval_s = float(eval_interval_s)
        self.dedup_window_s = float(dedup_window_s)
        self.max_incidents = int(max_incidents)
        self.stall_after_s = stall_after_s
        self.heartbeat_max_age_s = heartbeat_max_age_s
        self.trace_excerpt = int(trace_excerpt)
        self.flight_excerpt = int(flight_excerpt)
        self.objectives = tuple(objectives)
        self._burn = {o.name: _BurnState(o) for o in self.objectives}
        self._lock = threading.Lock()   # guards evaluation state
        self._steps = 0                 # observe_step() hot counter
        self._next_eval = -math.inf     # first poll() evaluates
        self._primed = False
        self._engine = None
        self._metrics = None
        self._recorder = None
        self._control = None
        # anomaly streams: name -> (phase, ewma, robust)
        self._anomaly_on = bool(anomaly_streams)
        mk = lambda: (EwmaDetector(alpha=ewma_alpha, k=ewma_k,
                                   warmup=min_samples),
                      RobustZDetector(z=z_threshold,
                                      min_samples=min_samples))
        self._streams: Dict[str, Tuple[str, EwmaDetector,
                                       RobustZDetector]] = {
            "step_latency": ("decode", *mk()),
            "queue_depth": ("queue", *mk()),
            "promotion_wait": ("kv_promotion", *mk()),
            "recompiles": ("compile", *mk()),
        }
        # deltas for stream readers / death detection / stall
        self._prev: Dict[str, float] = {}
        self._prev_deaths: Dict[str, float] = {}
        self._stall_since: Optional[float] = None
        self._orphans_prev: set = set()
        self._orphans_reported: set = set()
        self._incidents: "OrderedDict[str, Incident]" = OrderedDict()
        self._m_incidents = registry.counter(
            "ptpu_incidents_total",
            "watchtower incidents raised, by kind and dominant "
            "phase", labels=("kind", "phase"))

    # -- attachment ----------------------------------------------------
    def attach_engine(self, engine) -> "Watchtower":
        """Watch one in-process :class:`ServingEngine`: enables the
        orphaned-request detector and the recompile stream, installs
        the step hook, and captures the engine's flight recorder for
        incident snapshots."""
        self._engine = engine
        self._metrics = getattr(engine, "metrics", None)
        self._recorder = getattr(engine, "recorder", None)
        engine._watchtower = self
        return self

    def attach_recorder(self, recorder) -> "Watchtower":
        self._recorder = recorder
        return self

    def attach_control(self, control) -> "Watchtower":
        """Watch a :class:`serving.control.ControlPlane`: its snapshot
        rides ``to_json()`` (the doctor's control line) and the
        ``controller_flapping`` detector audits every dwell-gated
        controller against its own gate."""
        self._control = control
        return self

    # -- hot path ------------------------------------------------------
    def observe_step(self) -> None:
        """Called from the engine step hot path: ONE counter
        increment, nothing else (micro-asserted)."""
        self._steps += 1

    def poll(self) -> List[Incident]:
        """Cheap gate: one clock read + compare between window
        boundaries; a full evaluation once per ``eval_interval_s``."""
        if self.now() < self._next_eval:
            return []
        return self.flush()

    def flush(self) -> List[Incident]:
        """Force one evaluation now (window boundary, shutdown)."""
        with self._lock:
            now = float(self.now())
            self._next_eval = now + self.eval_interval_s
            return self._evaluate(now)

    # -- evaluation ----------------------------------------------------
    def _evaluate(self, now: float) -> List[Incident]:
        view = _MetricView(self.registry.to_json())
        new: List[Incident] = []
        self._eval_burn(now, view, new)
        self._eval_anomalies(now, view, new)
        self._eval_stall(now, view, new)
        self._eval_orphans(now, new)
        self._eval_deaths(now, view, new)
        self._eval_heartbeats(now, new)
        self._eval_control(now, new)
        self._primed = True
        return new

    # burn-rate engine -------------------------------------------------
    def _eval_burn(self, now: float, view: _MetricView,
                   out: List[Incident]) -> None:
        attr = None
        for obj in self.objectives:
            st = self._burn[obj.name]
            if obj.family is not None:
                h = view.hist(obj.family)
                total = int(h["count"]) if h else 0
                bad = (total - _good_count(h, obj.threshold_s)) \
                    if h else 0
                bad_rids: Tuple[int, ...] = ()
            elif self.telemetry is not None:
                if attr is None:
                    attr = self.telemetry.slo_attribution()
                key = obj.phase + "_s" if obj.phase != "failover" \
                    else "failover_replay_s"
                total, bad, rids = 0, 0, []
                for rec in attr:
                    total += 1
                    v = float(rec.get(key, 0.0))
                    if obj.phase == "prefill":
                        v += float(rec.get("chunked_prefill_s", 0.0))
                    if obj.phase == "dispatch":
                        v = float(rec.get("dispatch_rpc_s", 0.0))
                    if v > obj.threshold_s:
                        bad += 1
                        rids.append(int(rec["request_id"]))
                bad_rids = tuple(rids[-8:])
            else:
                continue
            if st.prev_total is None or total < st.prev_total \
                    or bad < st.prev_bad:
                # first sight, or a reset: re-prime, no deltas
                st.prev_total, st.prev_bad = total, bad
                continue
            d_total = total - st.prev_total
            d_bad = bad - st.prev_bad
            st.prev_total, st.prev_bad = total, bad
            st.push(now, d_total, d_bad)
            if not self._primed:
                continue
            fast = st.burn(now, obj.fast_window_s)
            slow = st.burn(now, obj.slow_window_s)
            ev_fast, _ = st.window(now, obj.fast_window_s)
            if fast >= obj.fast_burn and slow >= obj.slow_burn \
                    and ev_fast >= obj.min_events:
                phase, breakdown = self._dominant_phase(obj)
                share = breakdown.get(phase)
                pct = f"{100.0 * share:.0f}% {phase}" \
                    if share is not None else phase
                self._raise(out, kind="slo_burn", phase=phase,
                            key=obj.name, now=now,
                            summary=(f"{obj.name} burn "
                                     f"{fast:.1f}x/{slow:.1f}x "
                                     f"(fast/slow) over "
                                     f"{obj.threshold_s}s objective "
                                     f"— dominant: {pct}"),
                            detail={"objective": obj.name,
                                    "threshold_s": obj.threshold_s,
                                    "target": obj.objective,
                                    "fast_burn": round(fast, 3),
                                    "slow_burn": round(slow, 3),
                                    "breakdown": breakdown},
                            rids=bad_rids)

    def _dominant_phase(self, obj: SLOObjective
                        ) -> Tuple[str, Dict[str, float]]:
        """Dominant phase + normalized per-phase share from recent
        attribution records; falls back to the objective's declared
        phase when no telemetry plane is attached."""
        if self.telemetry is not None:
            sums: Dict[str, float] = {}
            for rec in self.telemetry.slo_attribution():
                for key, phase in _ATTR_PHASE_KEYS:
                    sums[phase] = sums.get(phase, 0.0) \
                        + float(rec.get(key, 0.0))
            total = sum(sums.values())
            if total > 0:
                breakdown = {p: round(v / total, 4)
                             for p, v in sorted(sums.items())
                             if v > 0}
                dom = max(breakdown, key=lambda p: breakdown[p])
                return dom, breakdown
        return (obj.phase or "decode"), {}

    # anomaly streams --------------------------------------------------
    def _delta(self, key: str, cur: float) -> float:
        prev = self._prev.get(key)
        self._prev[key] = cur
        if prev is None or cur < prev:
            return 0.0
        return cur - prev

    def _read_stream(self, name: str, view: _MetricView
                     ) -> Optional[float]:
        if name == "step_latency":
            h = view.hist("ptpu_serving_step_seconds")
            if h is None:
                return None
            dn = self._delta("step_latency_n", float(h["count"]))
            ds = self._delta("step_latency_s", float(h["sum"]))
            return (ds / dn) if dn > 0 else None
        if name == "queue_depth":
            return view.gauge("ptpu_serving_queue_depth")
        if name == "promotion_wait":
            h = view.hist("ptpu_kv_promotion_wait_seconds")
            if h is None:
                return None
            dn = self._delta("promotion_wait_n", float(h["count"]))
            ds = self._delta("promotion_wait_s", float(h["sum"]))
            return (ds / dn) if dn > 0 else None
        if name == "recompiles":
            eng = self._engine
            if eng is None or not hasattr(eng, "trace_counts"):
                return None
            n = 0
            for v in eng.trace_counts.values():
                n += len(v) and sum(v.values()) \
                    if isinstance(v, dict) else int(v)
            return self._delta("recompiles", float(n))
        return None

    def _eval_anomalies(self, now: float, view: _MetricView,
                        out: List[Incident]) -> None:
        if not self._anomaly_on:
            return
        for name, (phase, ewma, robust) in self._streams.items():
            x = self._read_stream(name, view)
            if x is None:
                continue
            # evaluate both (each must also LEARN the sample)
            t1 = ewma.update(x)
            t2 = robust.update(x)
            if t1 and t2 and self._primed:
                self._raise(out, kind="anomaly", phase=phase,
                            key=name, now=now,
                            summary=(f"{name} anomaly: sample "
                                     f"{x:.4g} vs ewma "
                                     f"{ewma.mean:.4g}"),
                            detail={"stream": name,
                                    "value": float(x),
                                    "ewma_mean": float(ewma.mean),
                                    "ewma_var": float(ewma.var)})

    # monotonic stall --------------------------------------------------
    def _eval_stall(self, now: float, view: _MetricView,
                    out: List[Incident]) -> None:
        if self.stall_after_s is None:
            return
        h = view.hist("ptpu_serving_step_seconds")
        steps = float(h["count"]) if h else float(self._steps)
        depth = view.gauge("ptpu_serving_queue_depth") or 0.0
        active = view.gauge("ptpu_serving_active_slots") or 0.0
        advanced = steps > self._prev.get("stall_steps", -1.0)
        self._prev["stall_steps"] = steps
        if advanced or (depth <= 0 and active <= 0):
            self._stall_since = None
            return
        if self._stall_since is None:
            self._stall_since = now
            return
        age = now - self._stall_since
        if age >= self.stall_after_s and self._primed:
            self._raise(out, kind="stall", phase="decode",
                        key="engine_steps", now=now,
                        summary=(f"engine stalled: {int(depth)} "
                                 f"queued / {int(active)} active "
                                 f"with no step for {age:.0f}s"),
                        detail={"queued": depth, "active": active,
                                "stalled_s": age})

    # orphaned requests ------------------------------------------------
    def _eval_orphans(self, now: float, out: List[Incident]) -> None:
        eng, m = self._engine, self._metrics
        if eng is None or m is None \
                or not hasattr(m, "inflight_phases") \
                or not hasattr(eng, "inflight_rids"):
            return
        inflight = m.inflight_phases()
        known = eng.inflight_rids()
        orphans = {rid for rid in inflight if rid not in known}
        # two consecutive evaluations: a submit racing this poll on
        # another thread must not page
        confirmed = (orphans & self._orphans_prev) \
            - self._orphans_reported
        self._orphans_prev = orphans
        for rid in sorted(confirmed):
            self._orphans_reported.add(rid)
            info = inflight.get(rid) or {}
            phase = str(info.get("phase", "queue"))
            self._raise(out, kind="request_orphaned", phase=phase,
                        key=f"rid={rid}", now=now,
                        summary=(f"request {rid} is tracked by "
                                 f"metrics but unknown to the "
                                 f"engine (dropped mid-"
                                 f"{phase}?)"),
                        detail={"rid": rid, "last_phase": phase,
                                "age_s": float(
                                    info.get("age_s", 0.0))},
                        rids=(rid,))

    # replica deaths ---------------------------------------------------
    def _eval_deaths(self, now: float, view: _MetricView,
                     out: List[Incident]) -> None:
        cur = view.counter_by_label(
            "ptpu_router_replica_deaths_total", "reason")
        prev, self._prev_deaths = self._prev_deaths, cur
        if not self._primed:
            return
        for reason, val in sorted(cur.items()):
            d = val - prev.get(reason, 0.0)
            if d <= 0:
                continue
            # a partition surfaces as the wire dying past the retry
            # budget (the worker process itself may be fine): that is
            # a DISPATCH-phase fault, not a worker death
            if reason == "unreachable":
                kind, phase = "partition", "dispatch"
            else:
                kind, phase = "worker_death", "failover"
            self._raise(out, kind=kind, phase=phase,
                        key=f"reason={reason}", now=now,
                        summary=(f"{int(d)} replica death(s), "
                                 f"reason={reason}"),
                        detail={"reason": reason, "deaths": int(d),
                                "failovers": view.counter_total(
                                    "ptpu_router_failovers_total")})

    # worker heartbeats ------------------------------------------------
    def _eval_heartbeats(self, now: float,
                         out: List[Incident]) -> None:
        if self.heartbeat_max_age_s is None \
                or self.telemetry is None:
            return
        for worker, snap in sorted(
                self.telemetry.worker_snapshots().items()):
            ts = snap.get("ts")
            if ts is None:
                continue
            age = now - float(ts)
            if age > self.heartbeat_max_age_s and self._primed:
                self._raise(out, kind="stall", phase="failover",
                            key=f"heartbeat={worker}", now=now,
                            summary=(f"worker {worker} silent for "
                                     f"{age:.0f}s (heartbeat bound "
                                     f"{self.heartbeat_max_age_s}s)"),
                            detail={"worker": worker,
                                    "age_s": float(age)})

    def _eval_control(self, now: float, out: List[Incident]) -> None:
        """``controller_flapping``: every dwell-gated controller can
        legally transition at most once per dwell (cool-down) period —
        more means the gate is broken (monkeypatched thresholds, a
        buggy controller swap) and the data plane is being thrashed."""
        cp = self._control
        if cp is None or not self._primed:
            return
        try:
            snap = cp.snapshot()
        except Exception:
            return
        checks = (("brownout", "queue", "flips", "dwell"),
                  ("chunk", "prefill", "adaptations", "dwell"),
                  ("autoscale", "failover", "actions", "cooldown"))
        for name, phase, n_key, gate_key in checks:
            st = snap.get(name)
            if not st:
                continue
            step = int(st.get("step", 0))
            gate = max(1, int(st.get(gate_key, 1)))
            n = int(st.get(n_key, 0))
            ceiling = step // gate + 1
            if step > 0 and n > ceiling:
                self._raise(
                    out, kind="controller_flapping", phase=phase,
                    key=f"controller={name}", now=now,
                    summary=(f"{name} controller flapping: {n} "
                             f"transitions in {step} steps exceeds "
                             f"its own gate ({gate}-step dwell "
                             f"allows {ceiling})"),
                    detail={"controller": name, "transitions": n,
                            "steps": step, "gate": gate,
                            "ceiling": ceiling})

    # -- incident plumbing ---------------------------------------------
    def _raise(self, out: List[Incident], *, kind: str, phase: str,
               key: str, now: float, summary: str,
               detail: Dict[str, Any],
               rids: Tuple[int, ...] = ()) -> None:
        fp = _fingerprint(kind, phase, key)
        inc = self._incidents.get(fp)
        if inc is not None \
                and now - inc.last_ts <= self.dedup_window_s:
            inc.count += 1
            inc.last_ts = now
            inc.detail = dict(detail)
            return
        inc = Incident(kind=kind, phase=phase, summary=summary,
                       ts=now, last_ts=now, fingerprint=fp,
                       detail=dict(detail),
                       request_ids=tuple(int(r) for r in rids),
                       flight=self._flight_excerpt(),
                       trace=self._trace_excerpt(rids))
        self._incidents[fp] = inc
        self._incidents.move_to_end(fp)
        while len(self._incidents) > self.max_incidents:
            self._incidents.popitem(last=False)
        self._m_incidents.labels(kind=kind, phase=phase).inc()
        out.append(inc)

    def _flight_excerpt(self) -> Tuple[dict, ...]:
        rec = self._recorder
        if rec is None or not hasattr(rec, "snapshot"):
            return ()
        try:
            return tuple(rec.snapshot()[-self.flight_excerpt:])
        except Exception:
            return ()

    def _trace_excerpt(self, rids: Tuple[int, ...]
                       ) -> Tuple[dict, ...]:
        tel = self.telemetry
        if tel is None:
            return ()
        try:
            if rids:
                spans: List[dict] = []
                for rid in rids[:4]:
                    spans.extend(tel.spans_for(rid))
                return tuple(spans[-self.trace_excerpt:])
            return tuple(tel.aligned_spans()[-self.trace_excerpt:])
        except Exception:
            return ()

    # -- readouts ------------------------------------------------------
    def incidents(self) -> List[Incident]:
        with self._lock:
            return list(self._incidents.values())

    def burn_rates(self) -> Dict[str, Dict[str, float]]:
        now = float(self.now())
        with self._lock:
            return {name: {"fast": st.burn(now,
                                           st.obj.fast_window_s),
                           "slow": st.burn(now,
                                           st.obj.slow_window_s)}
                    for name, st in self._burn.items()}

    def healthz(self) -> dict:
        incs = self.incidents()
        return {"ok": not incs, "incidents": len(incs),
                "steps": self._steps, "burn": self.burn_rates(),
                "ts": float(self.now())}

    def to_json(self) -> dict:
        """The ``/incidents`` payload (and the ``ptpu_doctor`` dump
        format): health summary, declared objectives, incidents —
        plus a ``speculation`` block when the attached engine decodes
        speculatively (accepted tokens/step, active proposer, tuner
        state), so one doctor dump answers "is speculation paying?"."""
        snap = {"health": self.healthz(),
                "objectives": [
                    {"name": o.name, "threshold_s": o.threshold_s,
                     "objective": o.objective, "family": o.family,
                     "phase": o.phase,
                     "windows_s": [o.fast_window_s,
                                   o.slow_window_s],
                     "burn_thresholds": [o.fast_burn, o.slow_burn]}
                    for o in self.objectives],
                "incidents": [i.to_json()
                              for i in self.incidents()]}
        eng = getattr(self, "_engine", None)
        if eng is not None and getattr(eng, "speculative", False):
            try:
                snap["speculation"] = eng.spec_stats()
            except Exception:
                pass
        if self._control is not None:
            try:
                snap["control"] = self._control.snapshot()
            except Exception:
                pass
        return snap

    def diagnose(self) -> str:
        return render_diagnosis(self.to_json())


_VERDICT = {"queue": "admission-bound", "dispatch": "rpc-bound",
            "prefill": "prefill-bound", "decode": "decode-bound",
            "handoff": "handoff-bound", "failover": "failover-bound",
            "kv_promotion": "promotion-bound",
            "compile": "recompile-bound"}


def render_diagnosis(snap: dict) -> str:
    """Human diagnosis from a watchtower JSON snapshot — the shared
    renderer behind ``Watchtower.diagnose()`` and
    ``tools/ptpu_doctor.py``. Example line::

        p99 TTFT burn: 78% queue-wait, decode healthy — admission-bound
    """
    health = snap.get("health") or {}
    incs = snap.get("incidents") or []
    lines: List[str] = []
    if not incs:
        lines.append("watchtower: healthy — no incidents")
    else:
        lines.append(f"watchtower: {len(incs)} incident(s)")
    for b_name, b in sorted((health.get("burn") or {}).items()):
        fast, slow = b.get("fast", 0.0), b.get("slow", 0.0)
        if fast or slow:
            lines.append(f"  burn[{b_name}]: fast {fast:.2f}x, "
                         f"slow {slow:.2f}x of error budget")
    spec = snap.get("speculation")
    if spec:
        line = (f"  speculation: {spec.get('proposer', 'ngram')} "
                f"accepted {spec.get('accepted_per_step', 0.0):.1f} "
                f"tok/step")
        tuner = spec.get("tuner")
        if tuner:
            st = (tuner.get("classes") or {}).get("greedy") or {}
            line += (f", tuner at k={st.get('k')}" if st.get("on")
                     else ", tuner off (k=1)")
        lines.append(line)
    ctl = snap.get("control")
    if ctl:
        parts = []
        b = ctl.get("brownout")
        if b:
            tiers = b.get("sheds_by_tier") or {}
            shed_s = ",".join(f"t{t}:{n}"
                              for t, n in sorted(tiers.items())) \
                or "none"
            parts.append(f"brownout L{b.get('level', 0)} "
                         f"sheds {shed_s}")
        c = ctl.get("chunk")
        if c:
            parts.append(f"chunk x{c.get('mult', 1)}")
        a = ctl.get("autoscale")
        if a:
            la = a.get("last_action")
            last = f"{la[0]}@{la[1]}" if la else "none"
            parts.append(f"replicas {a.get('replicas', 0)} "
                         f"last-scale {last}")
        if parts:
            lines.append("  control: " + "; ".join(parts))
    for inc in incs:
        phase = inc.get("phase", "?")
        verdict = _VERDICT.get(phase, f"{phase}-bound")
        breakdown = (inc.get("detail") or {}).get("breakdown") or {}
        if breakdown:
            parts = sorted(breakdown.items(),
                           key=lambda kv: -kv[1])
            top = ", ".join(f"{100 * v:.0f}% {p}-wait"
                            for p, v in parts[:2])
            healthy = [p for p in ("decode", "prefill", "queue")
                       if p not in dict(parts[:2])]
            tail = f", {healthy[0]} healthy" if healthy else ""
            lines.append(f"  {inc.get('kind')}: {top}{tail} "
                         f"— {verdict}")
        else:
            lines.append(f"  {inc.get('kind')}[{phase}]: "
                         f"{inc.get('summary', '')} — {verdict}")
        if inc.get("request_ids"):
            rids = ", ".join(str(r)
                             for r in inc["request_ids"][:8])
            lines.append(f"    offending rids: {rids}")
        if inc.get("count", 1) > 1:
            lines.append(f"    (deduped x{inc['count']} since "
                         f"t={inc.get('ts', 0):.0f})")
    return "\n".join(lines)
