"""Request-correlated spans.

A ``span`` is the host-side annotation every instrumented layer opens
around its hot sections. It forwards to ``profiler.RecordEvent`` — so
when a ``profiler.Profiler`` is recording, the span lands in BOTH the
chrome-trace host timeline and (via RecordEvent's TraceAnnotation
forwarding) the XPlane device trace — and it carries structured
attributes (``request_id`` first among them) into the chrome event's
``args``, which is what makes serving timelines correlatable: filter
the trace by ``args.request_id`` and one request's prefill/decode
steps line up across engine iterations.

Spans are cheap when nothing records: RecordEvent no-ops its event
append unless the profiler state machine is in RECORD.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

__all__ = ["Span", "span"]


class Span:
    """Context manager wrapping profiler.RecordEvent with attributes.

    ``set_attr`` may be called inside the span (attributes are read at
    exit, when the chrome event is emitted).
    """

    def __init__(self, name: str, request_id: Optional[int] = None,
                 **attrs: Any):
        self.name = name
        self.attrs: Dict[str, Any] = {}
        if request_id is not None:
            self.attrs["request_id"] = request_id
        self.attrs.update(attrs)
        self._ev = None

    def set_attr(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def __enter__(self) -> "Span":
        # lazy import: profiler is a peer package and observability
        # must stay importable on its own
        from .. import profiler
        self._ev = profiler.RecordEvent(self.name, args=self.attrs)
        self._ev.begin()
        return self

    def __exit__(self, *exc):
        if self._ev is not None:
            self._ev.end()
            self._ev = None
        return False


def span(name: str, request_id: Optional[int] = None,
         **attrs: Any) -> Span:
    """Open a host span; ``request_id``/attrs flow into the chrome
    trace event's ``args``::

        with span("serving.prefill", request_id=req.rid, bucket=32):
            ...
    """
    return Span(name, request_id=request_id, **attrs)
