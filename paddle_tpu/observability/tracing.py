"""Request-correlated spans + distributed trace propagation.

A ``span`` is the host-side annotation every instrumented layer opens
around its hot sections. It forwards to ``profiler.RecordEvent`` — so
when a ``profiler.Profiler`` is recording, the span lands in BOTH the
chrome-trace host timeline and (via RecordEvent's TraceAnnotation
forwarding) the XPlane device trace — and it carries structured
attributes (``request_id`` first among them) into the chrome event's
``args``, which is what makes serving timelines correlatable: filter
the trace by ``args.request_id`` and one request's prefill/decode
steps line up across engine iterations.

Since the serving path spans PROCESSES (frontdoor → router → RPC →
worker engine), spans also participate in distributed tracing:

- :class:`TraceContext` — (trace_id, parent span id), minted per
  request at the router, pickled onto the request AND every cluster
  RPC frame (``serving/cluster.py`` puts the active context in each
  message, alongside the virtual clock), so worker-side engine spans
  parent correctly.
- :class:`TraceBuffer` — a bounded per-process ring of COMPLETED
  spans. When one is installed (``install_trace_buffer``), every
  ``Span.__exit__`` records ``{name, t0, t1, pid, trace, attrs}``
  into it on the buffer's clock (workers install theirs with the
  engine's virtual-clock ``time_fn``). ``drain()`` hands the ring to
  the telemetry scrape; the cumulative ``drained_total`` /
  ``dropped_total`` counters let the merger detect a LOST scrape (or
  ring overflow) instead of silently truncating the timeline.
- request bindings (``bind_request``) — workers bind rid →
  TraceContext when a request arrives over RPC, so engine spans that
  only know a ``request_id`` resolve their trace without any engine
  code changes.

Spans are cheap when nothing records: RecordEvent no-ops its event
append unless the profiler state machine is in RECORD, and the trace
buffer is only consulted when one is installed.
"""
from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Span", "span", "TraceContext", "TraceBuffer",
           "install_trace_buffer", "current_trace_buffer",
           "bind_request", "unbind_request", "clear_bindings",
           "context_for", "active_context"]


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Distributed trace identity carried across the RPC boundary.

    Plain picklable value: ``trace_id`` names the whole request
    lifecycle (one per router submit), ``parent_span_id`` the span
    that minted/forwarded it. Deterministic ids (``req-<rid>``) keep
    chaos episodes replayable."""

    trace_id: str
    parent_span_id: int = 0

    @classmethod
    def for_request(cls, rid: int,
                    parent_span_id: int = 0) -> "TraceContext":
        return cls(trace_id=f"req-{int(rid)}",
                   parent_span_id=int(parent_span_id))


class TraceBuffer:
    """Bounded thread-safe ring of completed-span records.

    ``time_fn`` is the clock spans are stamped on — a worker passes
    its engine clock so virtual-clock episodes produce clock-aligned
    records across processes. The cumulative counters make scrape
    loss detectable: ``recorded_total == drained_total +
    dropped_total + len(ring)`` always holds, and a consumer that
    tracks the ``drained_total`` it has ingested can tell when a
    drain it never saw happened in between."""

    def __init__(self, capacity: int = 2048,
                 time_fn: Callable[[], float] = time.monotonic):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.now = time_fn
        self._ring: deque = deque()
        self._lock = threading.Lock()
        self.recorded_total = 0
        self.drained_total = 0
        self.dropped_total = 0

    def record(self, rec: dict) -> None:
        with self._lock:
            self.recorded_total += 1
            if len(self._ring) >= self.capacity:
                self._ring.popleft()
                self.dropped_total += 1
            self._ring.append(rec)

    def drain(self) -> List[dict]:
        """Take everything recorded since the last drain (oldest
        first); bumps ``drained_total`` by the number returned."""
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
            self.drained_total += len(out)
            return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


# -- process-global wiring (buffer + rid bindings + active stack) -----

_buffer: Optional[TraceBuffer] = None
_bindings: Dict[int, TraceContext] = {}
_bind_lock = threading.Lock()
_tls = threading.local()


def install_trace_buffer(
        buf: Optional[TraceBuffer]) -> Optional[TraceBuffer]:
    """Install the process trace buffer (None uninstalls). Returns
    the previously installed buffer so callers can restore it."""
    global _buffer
    prev = _buffer
    _buffer = buf
    return prev


def current_trace_buffer() -> Optional[TraceBuffer]:
    return _buffer


def bind_request(rid: int, ctx: Optional[TraceContext]) -> None:
    """rid → TraceContext: workers call this when a request arrives
    over RPC so engine spans (which only carry ``request_id``)
    resolve their trace id."""
    if ctx is None:
        return
    with _bind_lock:
        _bindings[int(rid)] = ctx


def unbind_request(rid: int) -> None:
    with _bind_lock:
        _bindings.pop(int(rid), None)


def clear_bindings() -> None:
    with _bind_lock:
        _bindings.clear()


def context_for(rid) -> Optional[TraceContext]:
    if rid is None:
        return None
    with _bind_lock:
        return _bindings.get(int(rid))


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def active_context() -> Optional[TraceContext]:
    """The context of the innermost open span that has one — what
    the cluster RPC client stamps on every outgoing frame."""
    st = _stack()
    return st[-1] if st else None


class Span:
    """Context manager wrapping profiler.RecordEvent with attributes.

    ``set_attr`` may be called inside the span (attributes are read at
    exit, when the chrome event is emitted). ``ctx`` attaches an
    explicit :class:`TraceContext`; without one, the request binding
    for ``attrs['request_id']`` and then the enclosing span's context
    are consulted. When a :class:`TraceBuffer` is installed the
    completed span is recorded into it at exit (even when the body
    raised — a failed stage is still part of the timeline).
    """

    def __init__(self, name: str, request_id: Optional[int] = None,
                 ctx: Optional[TraceContext] = None, **attrs: Any):
        self.name = name
        self.ctx = ctx
        self.attrs: Dict[str, Any] = {}
        if request_id is not None:
            self.attrs["request_id"] = request_id
        self.attrs.update(attrs)
        self._ev = None
        self._buf: Optional[TraceBuffer] = None
        self._t0 = 0.0
        self._eff: Optional[TraceContext] = None
        self._pushed = False

    def set_attr(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def __enter__(self) -> "Span":
        # lazy import: profiler is a peer package and observability
        # must stay importable on its own
        from .. import profiler
        self._ev = profiler.RecordEvent(self.name, args=self.attrs)
        self._ev.begin()
        self._eff = (self.ctx
                     or context_for(self.attrs.get("request_id"))
                     or active_context())
        if self._eff is not None:
            _stack().append(self._eff)
            self._pushed = True
        buf = _buffer
        if buf is not None:
            self._buf = buf
            self._t0 = float(buf.now())
        return self

    def __exit__(self, *exc):
        if self._ev is not None:
            self._ev.end()
            self._ev = None
        if self._pushed:
            st = _stack()
            if st:
                st.pop()
            self._pushed = False
        buf = self._buf
        if buf is not None:
            self._buf = None
            rec = {"name": self.name, "t0": self._t0,
                   "t1": float(buf.now()), "pid": os.getpid()}
            if self._eff is not None:
                rec["trace"] = self._eff.trace_id
                rec["parent"] = self._eff.parent_span_id
            if exc and exc[0] is not None:
                rec["error"] = getattr(exc[0], "__name__", str(exc[0]))
            if self.attrs:
                rec["attrs"] = dict(self.attrs)
            buf.record(rec)
        return False


def span(name: str, request_id: Optional[int] = None,
         ctx: Optional[TraceContext] = None, **attrs: Any) -> Span:
    """Open a host span; ``request_id``/attrs flow into the chrome
    trace event's ``args``::

        with span("serving.prefill", request_id=req.rid, bucket=32):
            ...
    """
    return Span(name, request_id=request_id, ctx=ctx, **attrs)
