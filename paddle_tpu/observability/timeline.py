"""Cluster telemetry plane: merge N processes into one timeline.

The serving path spans processes (frontdoor → router → RPC → worker
engines), so its observability is sharded: each worker owns a private
:class:`~.tracing.TraceBuffer` and :class:`~.registry.MetricRegistry`,
scraped over the ``telemetry`` RPC by the supervisor.
:class:`ClusterTelemetry` is the host-side accumulator that turns
those shards into the three cluster-level artifacts:

- **one chrome-trace JSON** (``chrome_trace``) with per-request lanes:
  every span becomes a ``ph:"X"`` event on (process pid, lane =
  request id), clock-aligned via the offset between the scraping
  host's clock and the ``now`` each payload carries (zero under the
  chaos virtual clock, which rides every RPC frame already). A
  failover shows up as the router's annotated
  ``router.failover.rehome`` span plus a flow arrow linking the
  request's two worker lanes through it.
- **one SLO-attribution record per request** (``slo_attribution``):
  queue / dispatch-RPC / prefill / chunked-prefill / decode /
  handoff / failover-replay seconds, from the same spans.
- **one Prometheus exposition** (``merged_prometheus``) served from
  the front door's ``/metrics``: counters summed across processes,
  gauges labeled ``worker=<label>`` (point-in-time values must stay
  distinguishable), histograms merged at the **bucket** level —
  never averaging percentiles; a quantile of merged buckets is
  meaningful, a mean of per-worker quantiles is not.

Trust rules the merge enforces rather than assumes:

- **Scrape loss is detected, not papered over.** Each payload carries
  the buffer's cumulative ``drained_total``/``dropped_total``; a gap
  between what was drained and what this plane ingested means a
  scrape response died on the wire (or the ring overflowed) and is
  recorded as a loss (``scrape_losses``) — the chaos trace-
  conservation law downgrades itself on losses instead of failing on
  a silently truncated timeline.
- **Counter resets add, never subtract.** A respawned (or
  soft-reclaimed) worker restarts its registry from zero; a sample
  below the previous one banks the old value as a completed
  incarnation (``base += last``) so cluster counters stay monotonic.
- **Label/schema collisions raise** ``MetricError``: same family at
  different type/labels/buckets across processes, a worker gauge
  already declaring a ``worker`` label, or two host registries
  exporting the same gauge sample.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

from .registry import MetricError, _escape_label, _fmt

__all__ = ["ClusterTelemetry"]

_HOST_PROCS = ("router", "frontdoor", "supervisor")
_US = 1e6  # chrome trace wants microseconds


def _span_rids(rec: dict) -> List[int]:
    """Request lane(s) a span record belongs to — batch spans
    (decode/verify) carry ``request_ids`` and fan out."""
    attrs = rec.get("attrs") or {}
    if attrs.get("request_id") is not None:
        return [int(attrs["request_id"])]
    ids = attrs.get("request_ids")
    if ids:
        return [int(r) for r in ids]
    return []


def _dur(rec: dict) -> float:
    return max(0.0, float(rec["t1"]) - float(rec["t0"]))


def _le_key(le: str) -> float:
    return math.inf if le == "+Inf" else float(le)


class ClusterTelemetry:
    """Accumulates scraped worker payloads + host registries/buffers
    and exports the merged artifacts. Single-episode lifecycle:
    ``begin_episode()`` clears everything accumulated (worker engines
    are reset to fresh buffers/registries at the same moment)."""

    def __init__(self):
        self._host_regs: List[Tuple[str, Any]] = []
        self._spans: List[dict] = []
        self._losses: List[dict] = []
        # (worker, pid) -> {"drained": int, "dropped": int}
        self._continuity: Dict[Tuple[str, int], Dict[str, int]] = {}
        # (worker, family, labelkey) -> reset-adjustment state
        self._counter_state: Dict[tuple, dict] = {}
        self._snapshots: Dict[str, dict] = {}   # worker -> effective
        self._worker_pids: Dict[str, int] = {}

    # -- registration ---------------------------------------------------
    def add_host_registry(self, registry, name: str) -> None:
        """A host-process registry (router, frontdoor) merged live —
        no scrape hop, so it is read at export time."""
        for n, r in self._host_regs:
            if r is registry:
                return
            if n == name:
                raise MetricError(
                    f"host registry name {name!r} already registered")
        self._host_regs.append((name, registry))

    def begin_episode(self) -> None:
        self._spans.clear()
        self._losses.clear()
        self._continuity.clear()
        self._counter_state.clear()
        self._snapshots.clear()
        self._worker_pids.clear()

    # -- ingestion ------------------------------------------------------
    def ingest_worker(self, worker: str, payload: dict,
                      host_now: Optional[float] = None) -> bool:
        """One ``telemetry`` scrape payload. Returns False when the
        payload is a duplicate (resent blob already ingested)."""
        pid = int(payload.get("pid") or 0)
        spans = list(payload.get("spans") or ())
        drained = int(payload.get("drained_total", len(spans)))
        dropped = int(payload.get("dropped_total", 0))
        key = (worker, pid)
        prev = self._continuity.get(key)
        if prev is not None and drained <= prev["drained"]:
            return False                       # replayed scrape blob
        seen = prev["drained"] if prev is not None else 0
        seen_drop = prev["dropped"] if prev is not None else 0
        before = drained - len(spans)          # drained prior to this
        if before > seen:
            self._losses.append(
                {"worker": worker, "pid": pid, "kind": "missed_scrape",
                 "lost_spans": before - seen})
        if dropped > seen_drop:
            self._losses.append(
                {"worker": worker, "pid": pid, "kind": "overflow",
                 "lost_spans": dropped - seen_drop})
        self._continuity[key] = {"drained": drained, "dropped": dropped}
        self._worker_pids[worker] = pid

        off = 0.0
        if host_now is not None and payload.get("now") is not None:
            off = float(host_now) - float(payload["now"])
        for rec in spans:
            tagged = dict(rec)
            tagged["proc"] = worker
            tagged["offset"] = off
            tagged.setdefault("pid", pid)
            self._spans.append(tagged)

        snap = payload.get("registry")
        if snap:
            self._snapshots[worker] = self._account(worker, snap)
        return True

    def ingest_host(self, spans: List[dict], proc: str = "router") -> None:
        """Spans drained from a host-process TraceBuffer (in-process:
        lossless, no clock offset)."""
        for rec in spans:
            tagged = dict(rec)
            tagged["proc"] = proc
            tagged["offset"] = 0.0
            self._spans.append(tagged)

    def rebaseline(self, worker: str, pid: int) -> None:
        """The worker deliberately swapped in a fresh trace buffer
        (engine reset / soft reclaim): drop continuity for this
        incarnation WITHOUT recording a loss, so the next scrape's
        restarted counters aren't mistaken for a replayed blob."""
        self._continuity.pop((worker, int(pid)), None)

    def forget(self, worker: str, pid: int,
               reason: str = "scrape_failed") -> None:
        """A scrape (usually the death-reap one) could not reach the
        worker: whatever its buffer held is gone. Recorded as a loss
        so consumers degrade instead of trusting a truncated view."""
        self._continuity.pop((worker, int(pid)), None)
        self._losses.append(
            {"worker": worker, "pid": int(pid), "kind": reason})

    # -- snapshot accounting (counter-reset detection) ------------------
    def _account(self, worker: str, snap: dict) -> dict:
        """Effective snapshot: reset-adjusted counters/histograms so a
        respawned worker's restart-from-zero ADDS an incarnation
        instead of subtracting (cluster counters stay monotonic)."""
        out = {"ts": snap.get("ts"), "metrics": {}}
        for name, fam in (snap.get("metrics") or {}).items():
            rows = []
            for s in fam.get("samples", ()):
                labels = dict(s.get("labels") or {})
                key = (worker, name,
                       tuple(sorted(labels.items())))
                if fam.get("type") == "counter":
                    cur = float(s.get("value", 0.0))
                    st = self._counter_state.setdefault(
                        key, {"base": 0.0, "last": 0.0})
                    if cur < st["last"]:       # new incarnation
                        st["base"] += st["last"]
                    st["last"] = cur
                    rows.append({"labels": labels,
                                 "value": st["base"] + cur})
                elif fam.get("type") == "histogram":
                    cur_b = dict(s.get("buckets") or {})
                    cur_s = float(s.get("sum", 0.0))
                    cur_n = int(s.get("count", 0))
                    st = self._counter_state.setdefault(
                        key, {"base": {"buckets": {}, "sum": 0.0,
                                       "count": 0},
                              "last": {"buckets": {}, "sum": 0.0,
                                       "count": 0}})
                    if cur_n < st["last"]["count"]:
                        b = st["base"]
                        for le, c in st["last"]["buckets"].items():
                            b["buckets"][le] = \
                                b["buckets"].get(le, 0) + c
                        b["sum"] += st["last"]["sum"]
                        b["count"] += st["last"]["count"]
                    st["last"] = {"buckets": cur_b, "sum": cur_s,
                                  "count": cur_n}
                    base = st["base"]
                    eff_b = {le: base["buckets"].get(le, 0) + c
                             for le, c in cur_b.items()}
                    rows.append({"labels": labels, "buckets": eff_b,
                                 "sum": base["sum"] + cur_s,
                                 "count": base["count"] + cur_n})
                else:                          # gauge: point-in-time
                    rows.append({"labels": labels,
                                 "value": float(s.get("value", 0.0))})
            out["metrics"][name] = {
                "type": fam.get("type"), "help": fam.get("help", ""),
                "label_names": list(fam.get("label_names") or ()),
                "samples": rows}
        return out

    # -- span access ----------------------------------------------------
    @property
    def spans(self) -> List[dict]:
        return list(self._spans)

    def aligned_spans(self) -> List[dict]:
        """Spans with clock-aligned ``t0``/``t1`` (offset applied),
        sorted by start time."""
        out = []
        for r in self._spans:
            off = float(r.get("offset", 0.0))
            a = dict(r)
            a["t0"] = float(r["t0"]) + off
            a["t1"] = float(r["t1"]) + off
            out.append(a)
        out.sort(key=lambda r: (r["t0"], r["t1"]))
        return out

    def spans_for(self, rid: int) -> List[dict]:
        rid = int(rid)
        return [r for r in self.aligned_spans()
                if rid in _span_rids(r)]

    def scrape_losses(self) -> List[dict]:
        return list(self._losses)

    def worker_snapshots(self) -> Dict[str, dict]:
        """Latest reset-adjusted registry snapshot per worker label."""
        return dict(self._snapshots)

    # -- chrome trace ---------------------------------------------------
    def chrome_trace(self) -> dict:
        """One merged chrome-trace object: pid = real process, lane
        (tid) = request id, flow arrows through every
        ``router.failover.rehome`` span linking the old and new
        worker lanes of the re-homed request."""
        events: List[dict] = []
        aligned = self.aligned_spans()
        procs: Dict[int, str] = {}
        lanes = set()
        for r in aligned:
            procs.setdefault(int(r.get("pid", 0)),
                             str(r.get("proc", "?")))
        for pid, proc in sorted(procs.items()):
            events.append({"ph": "M", "name": "process_name",
                           "pid": pid, "tid": 0,
                           "args": {"name": proc}})
        for r in aligned:
            rids = _span_rids(r) or [0]
            attrs = r.get("attrs") or {}
            pid = int(r.get("pid", 0))
            for rid in rids:
                args = dict(attrs)
                args["proc"] = r.get("proc")
                trace = r.get("trace") or (
                    f"req-{rid}" if rid else None)
                if trace:
                    args["trace_id"] = trace
                if r.get("error"):
                    args["error"] = r["error"]
                events.append({
                    "ph": "X", "name": r["name"], "cat": "span",
                    "pid": pid, "tid": rid,
                    "ts": r["t0"] * _US, "dur": _dur(r) * _US,
                    "args": args})
                if rid and (pid, rid) not in lanes:
                    lanes.add((pid, rid))
                    events.append({"ph": "M", "name": "thread_name",
                                   "pid": pid, "tid": rid,
                                   "args": {"name": f"req {rid}"}})
        events.extend(self._failover_flows(aligned))
        return {"traceEvents": events,
                "displayTimeUnit": "ms",
                "metadata": {"scrape_losses": self.scrape_losses()}}

    def _failover_flows(self, aligned: List[dict]) -> List[dict]:
        flows: List[dict] = []
        rehomes = [r for r in aligned
                   if r["name"] == "router.failover.rehome"]
        for k, rh in enumerate(rehomes):
            rids = _span_rids(rh)
            if not rids:
                continue
            rid = rids[0]
            rh_pid = int(rh.get("pid", 0))
            others = [r for r in aligned
                      if rid in _span_rids(r)
                      and int(r.get("pid", 0)) != rh_pid]
            pre = [r for r in others if r["t1"] <= rh["t1"] + 1e-9]
            post = [r for r in others if r["t0"] >= rh["t0"] - 1e-9]
            src = pre[-1] if pre else None
            dst = next((r for r in post if src is None
                        or int(r.get("pid", 0))
                        != int(src.get("pid", 0))), None)
            if src is None or dst is None:
                continue
            fid = f"failover-{rid}-{k}"
            flows.append({"ph": "s", "name": "failover",
                          "cat": "failover", "id": fid,
                          "pid": int(src["pid"]), "tid": rid,
                          "ts": src["t1"] * _US})
            flows.append({"ph": "t", "name": "failover",
                          "cat": "failover", "id": fid,
                          "pid": rh_pid, "tid": rid,
                          "ts": rh["t0"] * _US})
            flows.append({"ph": "f", "bp": "e", "name": "failover",
                          "cat": "failover", "id": fid,
                          "pid": int(dst["pid"]), "tid": rid,
                          "ts": dst["t0"] * _US})
        return flows

    # -- SLO attribution ------------------------------------------------
    def slo_attribution(self) -> List[dict]:
        """Per-request time accounting from the merged spans. Replay
        prefills (failover re-execution) bill to ``failover_replay_s``,
        not ``prefill_s`` — a re-homed request's first prefill already
        happened on the dead worker."""
        per: Dict[int, List[dict]] = {}
        for r in self.aligned_spans():
            for rid in _span_rids(r):
                per.setdefault(rid, []).append(r)
        out = []
        for rid in sorted(per):
            recs = per[rid]

            def named(*names):
                return [r for r in recs if r["name"] in names]

            prefills = named("serving.prefill")
            chunks = named("serving.chunk_prefill")
            replays = [r for r in prefills + chunks
                       if (r.get("attrs") or {}).get("replay")]
            first = [r for r in prefills if r not in replays]
            chunk_first = [r for r in chunks if r not in replays]
            dispatch = named("router.dispatch")
            rehomes = named("router.failover.rehome")
            queue_s = 0.0
            if (first or chunk_first) and dispatch:
                queue_s = max(0.0, min(r["t0"] for r in
                                       first + chunk_first)
                              - min(r["t1"] for r in dispatch))
            workers = sorted({str(r.get("proc")) for r in recs
                              if str(r.get("proc"))
                              not in _HOST_PROCS})
            out.append({
                "request_id": rid,
                "trace_id": f"req-{rid}",
                "queue_s": queue_s,
                "dispatch_rpc_s": sum(_dur(r) for r in dispatch),
                "prefill_s": sum(_dur(r) for r in first),
                # chunked prefill is its own SLO phase: the prompt's
                # KV was written across several bounded chunk steps
                # interleaved with other requests' decode
                "chunked_prefill_s": sum(_dur(r) for r in chunk_first),
                "decode_s": sum(_dur(r) for r in named(
                    "serving.decode", "serving.verify")),
                "handoff_s": sum(_dur(r) for r in named(
                    "serving.kv_handoff")),
                # cross-host wire hop inside the handoff: the KV
                # blocks' socket round-trip (serving/kv_wire.py),
                # billed separately so a slow network shows up as
                # kv_wire_s, not as generic handoff time
                "kv_wire_s": sum(_dur(r) for r in named(
                    "serving.kv_wire")),
                # KV tiering: time spent promoting demoted prefix
                # pages back onto device before the extend program —
                # the latency price of a warm-but-demoted prefix
                "kv_promotion_s": sum(_dur(r) for r in named(
                    "serving.kv_promote")),
                "failover_replay_s": sum(_dur(r) for r in replays)
                + sum(_dur(r) for r in rehomes),
                "failovers": len(rehomes),
                "workers": workers,
                "pids": sorted({int(r.get("pid", 0)) for r in recs}),
                "spans": len(recs)})
        return out

    # -- merged exposition ----------------------------------------------
    def _sources(self) -> List[Tuple[str, str, dict]]:
        srcs = [("host", name, reg.to_json())
                for name, reg in self._host_regs]
        srcs.extend(("worker", w, self._snapshots[w])
                    for w in sorted(self._snapshots))
        return srcs

    def merged_snapshot(self) -> dict:
        """The merged family tree behind ``merged_prometheus`` —
        counters summed, worker gauges re-labeled, histograms
        bucket-merged; raises :class:`MetricError` on any schema or
        label collision."""
        fams: Dict[str, dict] = {}
        for kind, src, snap in self._sources():
            for name, fam in (snap.get("metrics") or {}).items():
                ftype = fam.get("type")
                lnames = tuple(fam.get("label_names") or ())
                if ftype == "gauge" and kind == "worker":
                    if "worker" in lnames:
                        raise MetricError(
                            f"gauge {name} from worker {src} already "
                            f"declares a 'worker' label — merge would "
                            f"collide with the injected worker label")
                    lnames = lnames + ("worker",)
                ent = fams.get(name)
                if ent is None:
                    ent = fams[name] = {
                        "type": ftype, "help": fam.get("help", ""),
                        "label_names": lnames, "samples": {}}
                else:
                    if ent["type"] != ftype:
                        raise MetricError(
                            f"metric {name}: type conflict across "
                            f"processes ({ent['type']} vs {ftype})")
                    if ent["label_names"] != lnames:
                        raise MetricError(
                            f"metric {name}: label schema conflict "
                            f"across processes ({ent['label_names']} "
                            f"vs {lnames})")
                for s in fam.get("samples", ()):
                    labels = dict(s.get("labels") or {})
                    if ftype == "gauge" and kind == "worker":
                        labels["worker"] = src
                    key = tuple(str(labels.get(n, ""))
                                for n in ent["label_names"])
                    cur = ent["samples"].get(key)
                    if ftype == "counter":
                        ent["samples"][key] = \
                            (cur or 0.0) + float(s.get("value", 0.0))
                    elif ftype == "gauge":
                        if cur is not None:
                            raise MetricError(
                                f"gauge {name}{dict(zip(ent['label_names'], key))}: "
                                f"sample collision across processes — "
                                f"gauges merge by labeling, not "
                                f"summing")
                        ent["samples"][key] = float(s.get("value", 0.0))
                    else:                      # histogram
                        b = dict(s.get("buckets") or {})
                        if cur is None:
                            ent["samples"][key] = {
                                "buckets": b,
                                "sum": float(s.get("sum", 0.0)),
                                "count": int(s.get("count", 0))}
                        else:
                            if set(cur["buckets"]) != set(b):
                                raise MetricError(
                                    f"histogram {name}: bucket schema "
                                    f"mismatch across processes — "
                                    f"refusing a lossy merge")
                            for le, c in b.items():
                                cur["buckets"][le] += c
                            cur["sum"] += float(s.get("sum", 0.0))
                            cur["count"] += int(s.get("count", 0))
        return fams

    def merged_prometheus(self) -> str:
        """Cluster-wide Prometheus text exposition 0.0.4."""
        fams = self.merged_snapshot()
        lines: List[str] = []

        def lbl(names, values, extra=()):
            pairs = [f'{n}="{_escape_label(v)}"'
                     for n, v in zip(names, values)] + list(extra)
            return "{" + ",".join(pairs) + "}" if pairs else ""

        for name in sorted(fams):
            ent = fams[name]
            if ent["help"]:
                h = ent["help"].replace("\\", r"\\") \
                    .replace("\n", r"\n")
                lines.append(f"# HELP {name} {h}")
            lines.append(f"# TYPE {name} {ent['type']}")
            if ent["type"] == "histogram" and not ent["samples"]:
                # same zero-observation contract as
                # MetricRegistry.to_prometheus(): a registered-but-
                # silent histogram family still exposes _count/_sum
                lines.append(f'{name}_bucket{{le="+Inf"}} 0')
                lines.append(f"{name}_sum 0")
                lines.append(f"{name}_count 0")
            for key in sorted(ent["samples"]):
                val = ent["samples"][key]
                ls = lbl(ent["label_names"], key)
                if ent["type"] == "histogram":
                    for le in sorted(val["buckets"], key=_le_key):
                        bl = lbl(ent["label_names"], key,
                                 [f'le="{le}"'])
                        lines.append(
                            f"{name}_bucket{bl} {val['buckets'][le]}")
                    lines.append(f"{name}_sum{ls} {_fmt(val['sum'])}")
                    lines.append(f"{name}_count{ls} {val['count']}")
                else:
                    lines.append(f"{name}{ls} {_fmt(val)}")
        return "\n".join(lines) + "\n"
