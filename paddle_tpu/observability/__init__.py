"""paddle_tpu.observability — one telemetry substrate for every layer.

Three pieces (docs/OBSERVABILITY.md has the full guide):

- **Metrics registry** (``registry.py``): thread-safe ``Counter`` /
  ``Gauge`` / ``Histogram`` families with label sets and an injectable
  clock; Prometheus text exposition + JSON exporters. The process
  default (``default_registry()``) is what serving, jit, io, and
  distributed publish to.
- **Spans** (``tracing.py``): host annotations that forward to
  ``profiler.RecordEvent`` / ``jax.profiler.TraceAnnotation`` and carry
  structured args — serving spans carry request ids, so one request is
  traceable across engine iterations in the chrome trace.
- **Flight recorder** (``flight_recorder.py``): bounded ring of the
  last N step records (latency, occupancy, queue depth, compile
  events) dumped to disk when a step raises, the watchdog flags a dead
  peer, or an unhandled exception escapes; workers additionally spill
  the ring periodically so even a SIGKILL leaves a post-mortem.
- **Watchtower** (``watchtower.py``): the sensing layer over all of
  the above — multi-window SLO burn rates against declared objectives,
  EWMA + robust z-score anomaly detectors, stall/orphan/death
  detection, and deduped structured ``Incident`` records served from
  the front door's ``/healthz`` + ``/incidents`` endpoints and
  rendered by ``tools/ptpu_doctor.py``.
- **Cluster timeline** (``timeline.py``): merges per-process trace
  buffers and registry snapshots (scraped over the cluster
  ``telemetry`` RPC) into one chrome trace with per-request lanes, a
  per-request SLO attribution, and one cluster-wide Prometheus
  exposition (counters summed, gauges worker-labeled, histograms
  bucket-merged).

Instrumented out of the box: ``serving/engine.py`` (per-step spans,
queue/eviction/prefill counters, TTFT + inter-token + queue-wait
histograms), ``jit/static_function.py`` + ``jit/auto_capture.py``
(compile / cache-hit / graph-break / never-trace counters),
``distributed/watchdog.py`` (heartbeat-age gauge, failure counter,
dump hook), ``io/dataloader.py`` (batch-wait histogram), and
``profiler.Profiler.export_metrics`` (one chrome trace + one metrics
snapshot from the same run).
"""
from .registry import (Counter, Gauge, Histogram,  # noqa: F401
                       MetricError, MetricRegistry, default_registry)
from .tracing import (Span, span, TraceContext,  # noqa: F401
                      TraceBuffer, install_trace_buffer,
                      current_trace_buffer, bind_request,
                      unbind_request, clear_bindings, context_for,
                      active_context)
from .flight_recorder import FlightRecorder, default_recorder  # noqa: F401
from .timeline import ClusterTelemetry  # noqa: F401
from .watchtower import (Watchtower, Incident,  # noqa: F401
                         SLOObjective, DEFAULT_OBJECTIVES,
                         EwmaDetector, RobustZDetector,
                         render_diagnosis)

__all__ = ["Counter", "Gauge", "Histogram", "MetricError",
           "MetricRegistry", "default_registry", "Span", "span",
           "TraceContext", "TraceBuffer", "install_trace_buffer",
           "current_trace_buffer", "bind_request", "unbind_request",
           "clear_bindings", "context_for", "active_context",
           "FlightRecorder", "default_recorder", "ClusterTelemetry",
           "Watchtower", "Incident", "SLOObjective",
           "DEFAULT_OBJECTIVES", "EwmaDetector", "RobustZDetector",
           "render_diagnosis"]
