"""Crash flight recorder: the last N step records, dumped on failure.

The reference framework's comm-task flight recorder answers the only
question that matters when a multi-hour run dies: *what was the system
doing in the seconds before?* This is the host-side analog — a
bounded, thread-safe ring buffer that instrumented layers append
step records to (serving step latency + slot occupancy + queue depth,
compile events, watchdog sweeps), and that dumps itself to a JSON file
when

- an instrumented step raises (``ServingEngine.step`` wraps itself),
- the distributed watchdog flags a dead/hung peer, or
- the process hits an unhandled exception (``install_excepthook``).

Records are plain dicts so the dump is greppable without any tooling;
the ring bound means a week-long run costs the same memory as a
minute-long one.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

__all__ = ["FlightRecorder", "default_recorder"]


class FlightRecorder:
    def __init__(self, capacity: int = 256,
                 time_fn: Callable[[], float] = time.time,
                 dump_dir: Optional[str] = None, registry=None,
                 spill_path: Optional[str] = None,
                 spill_every: int = 0):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.now = time_fn
        self.dump_dir = dump_dir
        # registry whose snapshot embeds in dumps (None = the process
        # default; callers with an injected registry pass it at dump
        # time so the post-mortem carries THEIR metrics)
        self.registry = registry
        # SIGKILL survivability: a kill -9 never runs dump(), so a
        # worker can spill the ring to a well-known path every
        # spill_every records (and on SIGTERM) — the supervisor's
        # death dump attaches whatever the victim last spilled
        self.spill_path = spill_path
        self.spill_every = int(spill_every)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._dumps = 0
        self._prev_hook = None

    # -- recording -----------------------------------------------------
    def record(self, kind: str, **fields) -> dict:
        """Append one record; oldest records fall off past capacity."""
        with self._lock:
            rec = {"seq": self._seq, "t": float(self.now()),
                   "kind": kind, **fields}
            self._seq += 1
            self._ring.append(rec)
            due = (self.spill_path is not None and self.spill_every > 0
                   and self._seq % self.spill_every == 0)
        if due:
            self.spill()
        return rec

    def spill(self) -> Optional[str]:
        """Atomically write the ring to ``spill_path`` (tmp + rename;
        a kill mid-write leaves the previous spill intact). Errors are
        swallowed — spilling is best-effort insurance, never a reason
        to fail the step that triggered it."""
        path = self.spill_path
        if not path:
            return None
        try:
            payload = {"pid": os.getpid(),
                       "spilled_at": float(self.now()),
                       "records": self.snapshot()}
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=repr)
            os.replace(tmp, path)
            return path
        except Exception:
            return None

    def snapshot(self) -> List[dict]:
        """Oldest-to-newest copy of the ring."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- dumping -------------------------------------------------------
    def _default_path(self) -> str:
        d = self.dump_dir or os.environ.get("PTPU_FLIGHT_DIR") \
            or tempfile.gettempdir()
        os.makedirs(d, exist_ok=True)
        with self._lock:
            self._dumps += 1
            n = self._dumps
        return os.path.join(
            d, f"ptpu_flight_{os.getpid()}_{n:03d}.json")

    def dump(self, path: Optional[str] = None, reason: str = "",
             extra: Optional[Dict] = None, registry=None) -> str:
        """Write the ring (plus a metrics snapshot) to ``path`` and
        return it. The snapshot comes from ``registry``, else the
        recorder's own, else the process default. Callers on a crash
        path should wrap this in try/except so a full disk never masks
        the original error."""
        path = path or self._default_path()
        payload = {"reason": reason, "dumped_at": float(self.now()),
                   "pid": os.getpid(), "records": self.snapshot()}
        if extra:
            payload.update(extra)
        try:
            reg = registry if registry is not None else self.registry
            if reg is None:
                from .registry import default_registry
                reg = default_registry()
            payload["metrics"] = reg.to_json()
        except Exception:
            pass
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, default=repr)
        return path

    # -- crash hook ----------------------------------------------------
    def install_excepthook(self) -> "FlightRecorder":
        """Chain onto sys.excepthook: dump the ring before the default
        traceback printing on any unhandled exception."""
        if self._prev_hook is not None:
            return self
        prev = sys.excepthook

        def hook(exc_type, exc, tb):
            try:
                p = self.dump(
                    reason=f"unhandled {exc_type.__name__}: {exc}")
                print(f"[flight-recorder] dumped to {p}",
                      file=sys.stderr)
            except Exception:
                pass
            prev(exc_type, exc, tb)

        self._prev_hook = prev
        sys.excepthook = hook
        return self

    def uninstall_excepthook(self) -> None:
        if self._prev_hook is not None:
            sys.excepthook = self._prev_hook
            self._prev_hook = None


_default = FlightRecorder()


def default_recorder() -> FlightRecorder:
    """The process-global recorder the built-in layers append to."""
    return _default
