"""Incubate: experimental APIs (reference: python/paddle/incubate/, 42k LoC
— fused ops, MoE, ASP sparsity, autograd prim)."""
from . import nn  # noqa: F401
from . import moe  # noqa: F401
from .moe import MoELayer, GShardGate, SwitchGate  # noqa: F401
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import multiprocessing  # noqa: F401

# top-level incubate re-exports (python/paddle/incubate/__init__.py)
from ..geometric import (segment_max, segment_mean,  # noqa: F401
                         segment_min, segment_sum)
from ..geometric import sample_neighbors as graph_sample_neighbors  # noqa
from ..geometric import reindex_graph as graph_reindex  # noqa: F401
from ..geometric import send_u_recv as graph_send_recv  # noqa: F401


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop sampling: chained sample_neighbors + reindex
    (incubate/operators/graph_khop_sampler.py)."""
    import numpy as np
    from ..framework.tensor import Tensor
    from ..geometric import sample_neighbors
    cur = input_nodes
    seeds_list, neighbors_list, counts_list = [], [], []
    for k in sample_sizes:
        nb, cnt = sample_neighbors(row, colptr, cur, sample_size=k)
        seeds_list.append(np.asarray(
            cur.numpy() if isinstance(cur, Tensor) else cur))
        neighbors_list.append(np.asarray(nb.numpy()))
        counts_list.append(np.asarray(cnt.numpy()))
        cur = nb
    # union-compact ids over every hop, edges from ALL hops
    uniq = {}
    order = []
    def rid(v):
        if v not in uniq:
            uniq[v] = len(uniq)
            order.append(v)
        return uniq[v]
    for v in seeds_list[0].tolist():
        rid(v)
    srcs, dsts = [], []
    for seeds, nbs, cnts in zip(seeds_list, neighbors_list, counts_list):
        dst_global = np.repeat(seeds, cnts)
        for s_node, d_node in zip(nbs.tolist(), dst_global.tolist()):
            srcs.append(rid(s_node))
            dsts.append(rid(d_node))
    return (Tensor(np.asarray(srcs, np.int32)),
            Tensor(np.asarray(dsts, np.int32)),
            Tensor(np.asarray(order, np.int32)),
            Tensor(np.asarray(np.concatenate(counts_list), np.int32)))


def softmax_mask_fuse(x, mask, name=None):
    """Fused softmax(x + mask) (incubate/operators/softmax_mask_fuse.py);
    one XLA fusion on TPU."""
    from ..framework.tensor import apply_op
    import jax
    return apply_op(lambda a, m: jax.nn.softmax(a + m, axis=-1), x, mask,
                    _op_name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """softmax with causal (upper-triangle-masked) logits fused."""
    from ..framework.tensor import apply_op
    import jax
    import jax.numpy as jnp

    def f(a):
        s = a.shape[-1]
        causal = jnp.tril(jnp.ones((a.shape[-2], s), bool))
        return jax.nn.softmax(jnp.where(causal, a, -1e30), axis=-1)
    return apply_op(f, x, _op_name="softmax_mask_fuse_upper_triangle")


def identity_loss(x, reduction="none"):
    """Mark a tensor as a loss (IPU-oriented op); reduces per flag
    (reference codes: 0=sum, 1=mean, 2=none)."""
    if reduction in ("none", 2):
        return x
    if reduction in ("mean", 1):
        return x.mean()
    if reduction in ("sum", 0):
        return x.sum()
    raise ValueError(f"unknown reduction {reduction!r}")


class LookAhead:
    """Lookahead wrapper optimizer (incubate/optimizer/lookahead.py):
    every k steps, slow weights interpolate toward fast weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._slow = None
        self._steps = 0
        self._parameter_list = inner_optimizer._parameter_list

    def step(self):
        import jax.numpy as jnp
        self.inner.step()
        self._steps += 1
        params = [p for p in self._parameter_list if not p.stop_gradient]
        if self._slow is None:
            # copy: the inner optimizer's update rules donate the param
            # buffers, which would delete aliased references
            self._slow = [jnp.copy(p._data) for p in params]
        if self._steps % self.k == 0:
            for i, p in enumerate(params):
                slow = self._slow[i] + self.alpha * (
                    p._data.astype(self._slow[i].dtype) - self._slow[i])
                self._slow[i] = slow
                # copy, not astype: a no-op astype aliases `slow`, and the
                # next donated update would delete the stored slow weight
                p._data = jnp.array(slow, dtype=p._data.dtype, copy=True)

    def clear_grad(self, *a, **k):
        self.inner.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None


class ModelAverage:
    """EMA of parameters applied at eval (incubate/optimizer/
    modelaverage.py): accumulate during training, apply()/restore()
    around evaluation."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._parameter_list = list(parameters or [])
        self._sums = None
        self._count = 0
        self._backup = None

    def step(self):
        params = [p for p in self._parameter_list if not p.stop_gradient]
        if self._sums is None:
            self._sums = [p._data.astype("float32") * 0 for p in params]
        for i, p in enumerate(params):
            self._sums[i] = self._sums[i] + p._data.astype("float32")
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        params = [p for p in self._parameter_list if not p.stop_gradient]
        if not self._count:
            return
        self._backup = [p._data for p in params]
        for i, p in enumerate(params):
            p._data = (self._sums[i] / self._count).astype(p._data.dtype)

    def restore(self, executor=None):
        if self._backup is None:
            return
        params = [p for p in self._parameter_list if not p.stop_gradient]
        for p, b in zip(params, self._backup):
            p._data = b
        self._backup = None

    def minimize(self, loss, **kw):
        return None, None


from .. import inference  # noqa: F401  (paddle.incubate.inference alias)

from . import optimizer  # noqa: F401,E402
