"""Incubate: experimental APIs (reference: python/paddle/incubate/, 42k LoC
— fused ops, MoE, ASP sparsity, autograd prim)."""
from . import nn  # noqa: F401
from . import moe  # noqa: F401
from .moe import MoELayer, GShardGate, SwitchGate  # noqa: F401
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
