"""paddle.incubate.optimizer namespace (reference:
python/paddle/incubate/optimizer/__init__.py): LARS momentum, plus the
incubating wrappers (LookAhead lives at the top incubate level here)."""
from ..optimizer.lars_dgc import LarsMomentumOptimizer  # noqa: F401

__all__ = ["LarsMomentumOptimizer", "LookAhead"]


def __getattr__(name):
    if name == "LookAhead":
        from . import LookAhead
        return LookAhead
    raise AttributeError(name)
