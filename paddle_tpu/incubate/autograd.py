"""Higher-order / functional autograd (the "prim" system analog).

Reference: python/paddle/incubate/autograd/ — functional.py (jvp, vjp,
Jacobian, Hessian), primapi.py (forward_grad/grad over the primitive-op
program), plus paddle/fluid/prim composite gradient rules. The reference
needs a whole primitive-op dialect because its eager kernels have no
forward-mode rules; here every op IS a jax primitive with jvp/transpose
rules, so forward-mode, reverse-mode, and arbitrary composition
(hessian = jacfwd(jacrev)) come directly from the transform stack.
enable_prim/disable_prim exist for API compat and are no-ops: XLA always
sees decomposed primitives.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "forward_grad", "grad",
           "enable_prim", "disable_prim", "prim_enabled"]

_prim_flag = [False]


def enable_prim():
    _prim_flag[0] = True


def disable_prim():
    _prim_flag[0] = False


def prim_enabled() -> bool:
    return _prim_flag[0]


def _as_tuple(x):
    return tuple(x) if isinstance(x, (tuple, list)) else (x,)


def _unwrap(xs):
    return tuple(x._data if isinstance(x, Tensor) else jnp.asarray(x)
                 for x in _as_tuple(xs))


def _wrap(arrs):
    out = tuple(Tensor(a) for a in arrs)
    return out if len(out) > 1 else out[0]


def _pure(func: Callable) -> Callable:
    """Lift a Tensor-level function to operate on raw arrays."""
    def fn(*arrs):
        outs = func(*[Tensor(a) for a in arrs])
        outs = _as_tuple(outs)
        arrs_out = tuple(o._data if isinstance(o, Tensor)
                         else jnp.asarray(o) for o in outs)
        return arrs_out if len(arrs_out) > 1 else arrs_out[0]
    return fn


def jvp(func: Callable, xs, v=None):
    """Forward-mode: returns (func(xs), J @ v)
    (incubate/autograd/functional.py jvp contract; v defaults to ones)."""
    arrs = _unwrap(xs)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrs)
    else:
        tangents = tuple(t.astype(a.dtype) for t, a in
                         zip(_unwrap(v), arrs))
    out, tangent_out = jax.jvp(_pure(func), arrs, tangents)
    return (_wrap(_as_tuple(out)), _wrap(_as_tuple(tangent_out)))


def vjp(func: Callable, xs, v=None):
    """Reverse-mode: returns (func(xs), v^T @ J)
    (functional.py vjp; v defaults to ones over the output)."""
    arrs = _unwrap(xs)
    out, vjp_fn = jax.vjp(_pure(func), *arrs)
    outs = _as_tuple(out)
    if v is None:
        cot = tuple(jnp.ones_like(o) for o in outs)
    else:
        cot = tuple(c.astype(o.dtype) for c, o in zip(_unwrap(v), outs))
    grads = vjp_fn(cot if len(outs) > 1 else cot[0])
    return (_wrap(outs), _wrap(grads))


class Jacobian:
    """Lazy Jacobian (functional.py Jacobian): J[i, j] semantics over
    flattened output/input; computed with jacrev (reverse-mode, right for
    wide inputs) the first time it is materialized."""

    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self._func = func
        self._xs = xs
        self._is_batched = is_batched
        self._mat: Optional[np.ndarray] = None

    def _compute(self) -> np.ndarray:
        arrs = _unwrap(self._xs)
        if len(arrs) != 1:
            raise NotImplementedError("Jacobian over one input tensor")
        a = arrs[0]
        if self._is_batched:
            # func is defined on batched input; per-sample jacobian is
            # the batch diagonal of the full one: jac has shape
            # [B, out..., B, in...] -> diagonal over the two batch axes
            jac = jnp.asarray(jax.jacrev(_pure(self._func))(a))
            out_nd = jac.ndim - a.ndim
            diag = jnp.diagonal(jac, axis1=0, axis2=out_nd)
            self._mat = np.asarray(jnp.moveaxis(diag, -1, 0))
        else:
            jac = jnp.asarray(jax.jacrev(_pure(self._func))(a))
            out_sz = int(np.prod(jac.shape[:jac.ndim - a.ndim])) \
                if a.ndim else jac.size
            self._mat = np.asarray(jac).reshape(out_sz, a.size) \
                if a.ndim else np.asarray(jac)
        return self._mat

    @property
    def shape(self):
        if self._mat is not None:
            return self._mat.shape
        # derive without materializing (jacrev can cost seconds)
        a = _unwrap(self._xs)[0]
        out = jax.eval_shape(_pure(self._func), jax.ShapeDtypeStruct(
            a.shape, a.dtype))
        out_shape = out.shape if hasattr(out, "shape") else ()
        if self._is_batched:
            return tuple([a.shape[0]] + list(out_shape[1:]) +
                         list(a.shape[1:]))
        out_sz = int(np.prod(out_shape)) if out_shape else 1
        return (out_sz, int(np.prod(a.shape)) if a.shape else 1)

    def __getitem__(self, idx):
        if self._mat is None:
            self._compute()
        return Tensor(self._mat[idx])

    def numpy(self):
        if self._mat is None:
            self._compute()
        return self._mat


class Hessian(Jacobian):
    """Hessian of a scalar-output function (functional.py Hessian)."""

    def _compute(self) -> np.ndarray:
        arrs = _unwrap(self._xs)
        if len(arrs) != 1:
            raise NotImplementedError("Hessian over one input tensor")
        a = arrs[0]
        h = jax.hessian(_pure(self._func))(a)
        self._mat = np.asarray(jnp.asarray(h)).reshape(a.size, a.size)
        return self._mat


def forward_grad(outputs, inputs, grad_inputs=None):
    """primapi.forward_grad analog for static-graph Variables is not
    needed — use jvp on the function instead."""
    raise NotImplementedError(
        "forward_grad over recorded programs is superseded by "
        "incubate.autograd.jvp(func, xs)")


def grad(outputs, inputs, grad_outputs=None):
    """primapi.grad compat: delegates to paddle.autograd.grad."""
    from ..autograd import grad as eager_grad
    return eager_grad(outputs, inputs, grad_outputs)
