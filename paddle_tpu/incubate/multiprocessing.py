"""Tensor sharing across processes (reference:
python/paddle/incubate/multiprocessing/ — registers ForkingPickler
reducers so ``multiprocessing`` queues/pipes can carry Tensors through
shared memory instead of pickling the bytes;
reductions.py:95 ``_reduce_tensor``).

TPU-native rethink: device arrays are owned by XLA, so the shared
payload is the host copy in a ``multiprocessing.shared_memory`` block
(the reference's file_system strategy). The consumer rebuilds a Tensor
from the block; the producer unlinks it at GC. Useful for DataLoader
workers and any host-side pipeline (fleet_executor stages in separate
processes).
"""
from __future__ import annotations

import weakref
from multiprocessing import *  # noqa: F401,F403
from multiprocessing import reduction, shared_memory

import numpy as np

__all__ = []  # mirrors the reference: everything comes from stdlib mp

_OWNED: dict = {}


def _rebuild_tensor(shm_name, shape, dtype_str):
    from ..framework.tensor import Tensor
    shm = shared_memory.SharedMemory(name=shm_name)
    try:
        arr = np.ndarray(shape, dtype=np.dtype(dtype_str),
                         buffer=shm.buf).copy()
    finally:
        shm.close()
    import jax.numpy as jnp
    return Tensor(jnp.asarray(arr))


def _reduce_tensor(tensor):
    arr = np.asarray(tensor._data)
    shm = shared_memory.SharedMemory(create=True, size=max(arr.nbytes, 1))
    np.ndarray(arr.shape, dtype=arr.dtype, buffer=shm.buf)[...] = arr
    # producer keeps the block alive until the Tensor is collected
    _OWNED[shm.name] = shm
    weakref.finalize(tensor, _release, shm.name)
    return _rebuild_tensor, (shm.name, arr.shape, arr.dtype.str)


def _release(name):
    shm = _OWNED.pop(name, None)
    if shm is not None:
        shm.close()
        try:
            shm.unlink()
        except FileNotFoundError:
            pass


def init_reductions():
    from ..framework.tensor import Tensor
    reduction.ForkingPickler.register(Tensor, _reduce_tensor)


init_reductions()
