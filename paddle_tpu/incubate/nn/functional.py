"""Fused-op python APIs (reference: python/paddle/incubate/nn/functional/ —
fused_multi_transformer, fused_attention, fused_feedforward, fused rope,
fused_rms_norm, fused_layer_norm).

TPU-native: "fused" is the default on XLA — these wrappers express the same
contracts as compositions XLA fuses (or Pallas kernels for attention), so
reference incubate call sites port directly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor, apply_op
from ...nn import functional as F

__all__ = ["fused_rms_norm", "fused_layer_norm", "fused_rotary_position_embedding",
           "fused_dropout_add", "fused_linear", "fused_feedforward",
           "fused_attention", "fused_bias_act", "swiglu"]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    out = F.rms_norm(x, norm_weight, epsilon, begin_norm_axis)
    if norm_bias is not None:
        out = out + norm_bias
    return out, None


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=1, **kw):
    shape = x.shape[begin_norm_axis:]
    return F.layer_norm(x, shape, norm_weight, norm_bias, epsilon), None


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    rotary_emb_base=10000.0):
    """RoPE applied to [B, S, H, D] tensors (reference:
    incubate/nn/functional/fused_rotary_position_embedding.py)."""
    def rope(x_, sin_, cos_):
        if use_neox_rotary_style:
            x1, x2 = jnp.split(x_, 2, axis=-1)
            rotated = jnp.concatenate([-x2, x1], axis=-1)
        else:
            x1 = x_[..., 0::2]
            x2 = x_[..., 1::2]
            rotated = jnp.stack([-x2, x1], axis=-1).reshape(x_.shape)
        return x_ * cos_ + rotated * sin_

    def build_sin_cos(x_):
        B, S, H, D = x_.shape
        pos = jnp.arange(S, dtype=jnp.float32)
        inv = rotary_emb_base ** (-jnp.arange(0, D, 2, jnp.float32) / D)
        freqs = jnp.outer(pos, inv)  # [S, D/2]
        if use_neox_rotary_style:
            emb = jnp.concatenate([freqs, freqs], axis=-1)
        else:
            emb = jnp.repeat(freqs, 2, axis=-1)
        return (jnp.sin(emb)[None, :, None, :].astype(x_.dtype),
                jnp.cos(emb)[None, :, None, :].astype(x_.dtype))

    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        if sin is None:
            def f(a):
                s_, c_ = build_sin_cos(a)
                return rope(a, s_, c_)
            outs.append(apply_op(f, t, _op_name="fused_rope"))
        else:
            outs.append(apply_op(lambda a, s_, c_: rope(a, s_, c_), t, sin,
                                 cos, _op_name="fused_rope"))
    return tuple(outs)


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train",
                      name=None):
    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    if transpose_weight:
        from ...ops.linalg import matmul
        out = matmul(x, weight, transpose_y=True)
        return out + bias if bias is not None else out
    return F.linear(x, weight, bias)


def swiglu(x, y=None, name=None):
    if y is None:
        a, b = None, None
        def f(v):
            a_, b_ = jnp.split(v, 2, axis=-1)
            return jax.nn.silu(a_) * b_
        return apply_op(f, x, _op_name="swiglu")
    return apply_op(lambda a, b: jax.nn.silu(a) * b, x, y,
                    _op_name="swiglu")


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    if bias is not None:
        x = x + bias
    return getattr(F, act_method)(x)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, name=None):
    residual = x
    if pre_layer_norm and ln1_scale is not None:
        x = F.layer_norm(x, [x.shape[-1]], ln1_scale, ln1_bias, ln1_epsilon)
    h = F.linear(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    h = F.dropout(h, dropout1_rate, training=training)
    h = F.linear(h, linear2_weight, linear2_bias)
    h = F.dropout(h, dropout2_rate, training=training)
    out = residual + h
    if not pre_layer_norm and ln2_scale is not None:
        out = F.layer_norm(out, [out.shape[-1]], ln2_scale, ln2_bias,
                           ln2_epsilon)
    return out


def fused_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                    pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                    ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                    linear_bias=None, cache_kv=None, attn_mask=None,
                    dropout_rate=0.5, attn_dropout_rate=0.5,
                    ln_epsilon=1e-5, training=True, num_heads=None,
                    name=None):
    """Fused MHA block (reference fused_attention op). qkv_weight layout
    [3, num_heads, head_dim, embed_dim]."""
    residual = x
    if pre_layer_norm and pre_ln_scale is not None:
        x = F.layer_norm(x, [x.shape[-1]], pre_ln_scale, pre_ln_bias,
                         pre_ln_epsilon)
    three, H, hd, D = qkv_weight.shape
    w = qkv_weight.reshape([3 * H * hd, D])
    from ...ops.linalg import matmul
    qkv = matmul(x, w, transpose_y=True)
    if qkv_bias is not None:
        qkv = qkv + qkv_bias.reshape([3 * H * hd])
    B, S = x.shape[0], x.shape[1]
    qkv = qkv.reshape([B, S, 3, H, hd])
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    out = F.scaled_dot_product_attention(q, k, v, attn_mask,
                                         attn_dropout_rate,
                                         training=training)
    out = out.reshape([B, S, H * hd])
    out = F.linear(out, linear_weight, linear_bias)
    out = F.dropout(out, dropout_rate, training=training)
    out = residual + out
    if not pre_layer_norm and ln_scale is not None:
        out = F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias,
                           ln_epsilon)
    return out
