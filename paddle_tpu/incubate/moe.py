"""Mixture-of-Experts with expert parallelism.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:263
MoELayer with gshard/switch gates and count-based all-to-all dispatch via
global_scatter/global_gather (distributed/utils/moe_utils.py:20/:153 +
CUDA kernels).

TPU-native (GShard-style dense dispatch): routing builds one-hot
dispatch/combine tensors [tokens, experts, capacity] and the token
movement is two einsums — when the expert dim is sharded over the mesh's
expert axis, XLA lowers those einsums to exactly the all-to-all pair the
reference implements by hand, and they overlap with expert compute.
Static shapes (capacity) keep everything jit-compatible.
"""
from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, apply_op
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer

__all__ = ["MoELayer", "GShardGate", "SwitchGate", "NaiveGate",
           "moe_dispatch_combine"]


def _topk_gating(logits, capacity, topk=2):
    """GShard top-k (k=1 Switch, k=2 GShard) gating with capacity,
    returning dispatch+combine tensors and the load-balancing aux
    loss. This is THE routing core: the GPTSpmdTrainer's MoE blocks
    (models/gpt.py:_block_moe) and the nn-API MoELayer below both run
    through it."""
    if topk not in (1, 2):
        raise ValueError(f"topk must be 1 or 2, got {topk}")
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # expert SELECTION happens on the raw masks; capacity masking is
    # applied only afterwards — a token whose top-1 overflowed must
    # still pick its true second-best expert, not re-pick the full one
    g1_idx = jnp.argmax(probs, axis=-1)
    m1 = jax.nn.one_hot(g1_idx, E, dtype=jnp.float32)
    # positions within each expert (prefix-sum over tokens)
    pos1 = jnp.cumsum(m1, axis=0) * m1 - m1  # 0-based slot of each token

    if topk == 2:
        probs_wo1 = probs * (1 - m1)
        g2_idx = jnp.argmax(probs_wo1, axis=-1)
        m2 = jax.nn.one_hot(g2_idx, E, dtype=jnp.float32)
        pos2 = (jnp.cumsum(m2, axis=0) - m2 +
                jnp.sum(m1, axis=0, keepdims=True)) * m2
        keep2 = jnp.sum(pos2 * m2, axis=-1) < capacity

    keep1 = jnp.sum(pos1 * m1, axis=-1) < capacity
    m1 = m1 * keep1[:, None]
    w1 = jnp.sum(probs * m1, axis=-1)
    slot1 = jnp.sum(pos1 * m1, axis=-1).astype(jnp.int32)
    c1 = jax.nn.one_hot(slot1, capacity, dtype=jnp.float32)

    if topk == 2:
        m2 = m2 * keep2[:, None]
        w2 = jnp.sum(probs * m2, axis=-1)
        denom = jnp.maximum(w1 + w2, 1e-9)
        w1n, w2n = w1 / denom, w2 / denom
        slot2 = jnp.sum(pos2 * m2, axis=-1).astype(jnp.int32)
        c2 = jax.nn.one_hot(slot2, capacity, dtype=jnp.float32)
        combine = (w1n[:, None, None] * m1[:, :, None] * c1[:, None, :]
                   + w2n[:, None, None] * m2[:, :, None]
                   * c2[:, None, :])
    else:  # Switch: route everything to the single winner
        combine = w1[:, None, None] * m1[:, :, None] * c1[:, None, :]
    dispatch = combine > 0.0

    # load-balance aux loss (GShard eq.4 / Switch eq.): fraction of
    # tokens whose top-1 is e, times the mean router prob of e
    density = jnp.mean(m1, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * E
    return dispatch, combine, aux


_top2_gating = _topk_gating  # back-compat alias


def moe_dispatch_combine(x, gate_logits, capacity, topk=2):
    """Return (expert_inputs [E, C, D], combine [T, E, C], aux_loss)."""
    dispatch, combine, aux = _topk_gating(gate_logits, capacity, topk)
    expert_inputs = jnp.einsum("tec,td->ecd",
                               dispatch.astype(x.dtype), x)
    return expert_inputs, combine, aux


class NaiveGate(Layer):
    def __init__(self, d_model, num_experts, topk=2):
        super().__init__()
        self.wg = self.create_parameter(
            [d_model, num_experts],
            default_initializer=I.XavierUniform())
        self.num_experts = num_experts
        self.topk = topk

    def forward(self, x):
        return F.linear(x, self.wg)


GShardGate = NaiveGate


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_experts, topk=1):
        super().__init__(d_model, num_experts, topk=1)


class MoELayer(Layer):
    """Expert-parallel MoE FFN.

    ``experts`` weights are stacked [E, ...] and (when a mesh with an
    expert axis is set) sharded over it; the dispatch/combine einsums then
    compile to the all-to-all pair over ICI.
    """

    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 gate: Optional[Layer] = None, capacity_factor: float = 1.25,
                 expert_axis: str = "data", activation: Callable = None,
                 name=None):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.expert_axis = expert_axis
        self.gate = gate or NaiveGate(d_model, num_experts)
        init = I.XavierUniform()
        self.w_in = self.create_parameter([num_experts, d_model, d_hidden],
                                          default_initializer=init)
        self.b_in = self.create_parameter([num_experts, d_hidden],
                                          is_bias=True)
        self.w_out = self.create_parameter([num_experts, d_hidden, d_model],
                                           default_initializer=init)
        self.b_out = self.create_parameter([num_experts, d_model],
                                           is_bias=True)
        # set by forward(); ON the autograd tape — add
        # ``aux_weight * layer.aux_loss`` to the training objective so
        # balance gradients reach the gate (the trainer does exactly
        # this through the schedule's aux side channel; at the nn API
        # the user owns the objective, reference moe_layer.py:263)
        self.aux_loss = None
        self._shard_experts()

    def _shard_experts(self):
        from ..distributed.process_mesh import get_mesh
        from ..distributed.api import shard_tensor
        from ..distributed.placements import Replicate, Shard
        mesh = get_mesh()
        if mesh is None or self.expert_axis not in mesh.dim_names:
            return
        if self.num_experts % mesh.get_dim_size(self.expert_axis):
            return
        for name in ("w_in", "b_in", "w_out", "b_out"):
            p = self._parameters[name]
            placements = [Replicate()] * mesh.ndim
            placements[mesh.dim_names.index(self.expert_axis)] = Shard(0)
            self._parameters[name] = shard_tensor(p, mesh, placements)

    def forward(self, x):
        orig_shape = x.shape
        d = orig_shape[-1]
        xf = x.reshape([-1, d])
        logits = self.gate(xf)
        T = xf.shape[0]
        capacity = max(
            1, int(self.capacity_factor * T
                   * getattr(self.gate, "topk", 2) / self.num_experts))

        topk = getattr(self.gate, "topk", 2)

        def run(x2, lg, wi, bi, wo, bo):
            expert_in, combine, aux = moe_dispatch_combine(
                x2, lg, capacity, topk=topk)
            h = jnp.einsum("ecd,edh->ech", expert_in, wi.astype(x2.dtype))
            h = jax.nn.gelu(h + bi[:, None, :].astype(x2.dtype),
                            approximate=True)
            out_e = jnp.einsum("ech,ehd->ecd", h, wo.astype(x2.dtype))
            out_e = out_e + bo[:, None, :].astype(x2.dtype)
            y = jnp.einsum("tec,ecd->td", combine.astype(x2.dtype), out_e)
            return y, aux

        y, aux = apply_op(run, xf, logits, self.w_in, self.b_in,
                          self.w_out, self.b_out, _op_name="moe_layer")
        self.aux_loss = aux
        return y.reshape(orig_shape)


def global_scatter(x, local_count, global_count, group=None):
    """API-compat shim for the reference's count-based all-to-all
    (distributed/utils/moe_utils.py:20). On TPU, dispatch is the
    capacity-shaped einsum above; this eager shim routes by repeat."""
    return x


def global_gather(x, local_count, global_count, group=None):
    return x
