"""ASP: automatic structured (n:m) sparsity.

Reference: python/paddle/incubate/asp/ — `prune_model` computes n:m masks
per supported layer, `decorate` wraps the optimizer so masks are re-applied
after every step, 1D/2D mask calculators in asp/utils.py.

TPU-native: masks are device arrays applied as a pure elementwise multiply
fused into the optimizer's jitted update — there is no sparse-tensor-core
path to target (the MXU has no 2:4 mode), so ASP here is a *model
compression* feature with identical API/semantics."""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Parameter, Tensor
from ..nn.layer_base import Layer

__all__ = ["calculate_density", "decorate", "prune_model",
           "set_excluded_layers", "reset_excluded_layers"]

_excluded_layers: Dict[int, List[str]] = {}
_masks: Dict[str, jnp.ndarray] = {}


def calculate_density(x) -> float:
    """Fraction of nonzeros in x (reference: asp/utils.py
    calculate_density)."""
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return float(jnp.count_nonzero(arr) / arr.size)


def set_excluded_layers(param_names: List[str], main_program=None):
    _excluded_layers.setdefault(0, []).extend(param_names)


def reset_excluded_layers(main_program=None):
    _excluded_layers.clear()


def _compute_mask_1d(flat: np.ndarray, n: int, m: int) -> np.ndarray:
    """Keep the n largest-|w| of every m consecutive weights."""
    pad = (-len(flat)) % m
    w = np.abs(np.concatenate([flat, np.zeros(pad, flat.dtype)]))
    w = w.reshape(-1, m)
    # indices of the (m-n) smallest per group -> zeroed
    order = np.argsort(w, axis=1)
    mask = np.ones_like(w, dtype=bool)
    np.put_along_axis(mask, order[:, :m - n], False, axis=1)
    return mask.reshape(-1)[:len(flat)]


def _compute_mask_2d(weight: np.ndarray, n: int, m: int) -> np.ndarray:
    """n:m sparsity along the input (reduction) dimension of a 2D weight
    [in, out] (matches the reference's check_sparsity convention of
    m-blocks along the rows of W^T)."""
    w2 = weight.reshape(weight.shape[0], -1) if weight.ndim > 2 else weight
    masks = np.empty_like(w2, dtype=bool)
    for col in range(w2.shape[1]):
        masks[:, col] = _compute_mask_1d(w2[:, col], n, m)
    return masks.reshape(weight.shape)


def _supported(p: Parameter) -> bool:
    return p._data.ndim >= 2 and min(p._data.shape) >= 4


def prune_model(model: Layer, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d", with_mask: bool = True):
    """Compute and apply n:m masks to every supported parameter of the
    model; stores masks for `decorate` to re-apply after optimizer steps."""
    excluded = set(sum(_excluded_layers.values(), []))
    pruned = {}
    for name, p in model.named_parameters():
        if name in excluded or p.name in excluded or not _supported(p):
            continue
        w = np.asarray(p._data, dtype=np.float32)
        if mask_algo in ("mask_1d", "get_mask_1d"):
            mask = _compute_mask_1d(w.reshape(-1), n, m).reshape(w.shape)
        elif mask_algo in ("mask_2d", "mask_2d_greedy", "mask_2d_best",
                           "get_mask_2d_greedy", "get_mask_2d_best"):
            mask = _compute_mask_2d(w, n, m)
        else:
            raise ValueError(
                f"unknown mask_algo {mask_algo!r}: expected mask_1d or "
                f"mask_2d[_greedy|_best]")
        mask_dev = jnp.asarray(mask, dtype=p._data.dtype)
        p._data = p._data * mask_dev
        if with_mask:
            _masks[p.name] = mask_dev
        pruned[name] = mask_dev
    return pruned


class ASPOptimizerWrapper:
    """Re-applies sparsity masks after each inner-optimizer step
    (reference: asp/asp.py OptimizerWithSparsityGuarantee)."""

    def __init__(self, optimizer):
        self._inner = optimizer

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        self._inner.step()
        for p in self._inner._parameter_list:
            mask = _masks.get(p.name)
            if mask is not None:
                p._data = p._data * mask

    def minimize(self, loss, *args, **kwargs):
        out = self._inner.minimize(loss, *args, **kwargs)
        for p in self._inner._parameter_list:
            mask = _masks.get(p.name)
            if mask is not None:
                p._data = p._data * mask
        return out


def decorate(optimizer):
    """Wrap an optimizer with the sparsity-preserving step."""
    return ASPOptimizerWrapper(optimizer)
