"""GradScaler (reference: python/paddle/amp/grad_scaler.py:657) —
dynamic loss scaling with found-inf step skipping.

TPU note: with bf16 (the TPU-native dtype) scaling is unnecessary; the
scaler still tracks the full protocol so float16 workloads and checkpoints
behave like the reference.
"""
from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from ..framework.tensor import Tensor, no_grad

__all__ = ["GradScaler", "AmpScaler"]


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=65536.0,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        inv = 1.0 / self._scale
        found = False
        with no_grad():
            for p in optimizer._parameter_list:
                if p.grad is None:
                    continue
                g = p.grad._data.astype(jnp.float32) * inv
                if not bool(jnp.isfinite(g).all()):
                    found = True
                p.grad = Tensor(g.astype(p.grad._data.dtype),
                                stop_gradient=True)
        self._found_inf = found
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        if not self._enable:
            return
        if self._dynamic:
            if self._found_inf:
                self._bad_steps += 1
                self._good_steps = 0
                if self._bad_steps >= self._decr_every_n_nan_or_inf:
                    self._scale = max(self._scale * self._decr_ratio, 1.0)
                    self._bad_steps = 0
            else:
                self._good_steps += 1
                self._bad_steps = 0
                if self._good_steps >= self._incr_every_n_steps:
                    self._scale *= self._incr_ratio
                    self._good_steps = 0
        self._found_inf = False
        self._unscaled = False

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()
        optimizer.clear_grad()

    def get_loss_scaling(self) -> float:
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self) -> Dict:
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


AmpScaler = GradScaler
