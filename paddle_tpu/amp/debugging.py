"""Numerical debugging (reference: python/paddle/amp/debugging.py —
check_numerics, tensor stats; plus FLAGS_check_nan_inf hooks in
fluid/eager/nan_inf_utils.cc which here live in framework.tensor.apply_op)."""
from __future__ import annotations

import contextlib
from enum import Enum
from typing import List, Tuple

import jax.numpy as jnp
import numpy as np

from ..framework.flags import set_flags
from ..framework.tensor import Tensor

__all__ = ["enable_operator_stats_collection", "check_numerics",
           "enable_tensor_checker", "disable_tensor_checker",
           "collect_operator_numerical_stats", "DebugMode",
           "TensorCheckerConfig"]


class DebugMode(Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None):
        self.enable = enable
        self.debug_mode = debug_mode


def enable_tensor_checker(config: TensorCheckerConfig):
    set_flags({"FLAGS_check_nan_inf": config.enable})
    set_flags({"FLAGS_check_nan_inf_level":
               0 if config.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT
               else 1})


def disable_tensor_checker():
    set_flags({"FLAGS_check_nan_inf": False})


def check_numerics(tensor: Tensor, op_type: str = "", var_name: str = "",
                   debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    """Return (num_nan, num_inf, num_zero) stats; abort per mode."""
    a = tensor._data
    n_nan = int(jnp.isnan(a).sum())
    n_inf = int(jnp.isinf(a).sum())
    n_zero = int((a == 0).sum())
    if n_nan or n_inf:
        msg = (f"[check_numerics] op={op_type} var={var_name}: "
               f"{n_nan} NaN, {n_inf} Inf")
        if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
            raise FloatingPointError(msg)
        print("WARNING:", msg)
    return (Tensor(jnp.asarray(n_nan)), Tensor(jnp.asarray(n_inf)),
            Tensor(jnp.asarray(n_zero)))


@contextlib.contextmanager
def enable_operator_stats_collection():
    stats: List[Tuple[str, str]] = []
    yield stats


def collect_operator_numerical_stats(tensor: Tensor):
    a = np.asarray(tensor._data, dtype=np.float64)
    return {"min": float(a.min()), "max": float(a.max()),
            "mean": float(a.mean()),
            "num_nan": int(np.isnan(a).sum()),
            "num_inf": int(np.isinf(a).sum())}
