"""Numerical debugging (reference: python/paddle/amp/debugging.py —
check_numerics, operator stats, compare_accuracy; plus
FLAGS_check_nan_inf hooks in fluid/eager/nan_inf_utils.cc which here live
in framework.tensor.apply_op, and the in-graph accuracy_check kernel
phi/kernels/accuracy_check_kernel.h / ops.yaml:31)."""
from __future__ import annotations

import contextlib
import csv
import json
import os
from collections import defaultdict
from enum import Enum
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..framework import tensor as _tensor_mod
from ..framework.flags import set_flags
from ..framework.tensor import Tensor

__all__ = [
    "DebugMode", "TensorCheckerConfig", "check_numerics",
    "enable_operator_stats_collection",
    "disable_operator_stats_collection", "collect_operator_stats",
    "enable_tensor_checker", "disable_tensor_checker",
    "compare_accuracy", "check_layer_numerics",
    "set_checked_op_list", "set_skipped_op_list",
    "collect_operator_numerical_stats", "accuracy_check",
    "save_tensor_stats",
]


class DebugMode(Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


_checked_ops: Optional[set] = None
_skipped_ops: set = set()


def set_checked_op_list(checked_op_list: Sequence[str] | None) -> None:
    global _checked_ops
    _checked_ops = set(checked_op_list) if checked_op_list else None


def set_skipped_op_list(skipped_op_list: Sequence[str] | None) -> None:
    global _skipped_ops
    _skipped_ops = set(skipped_op_list) if skipped_op_list else set()


class TensorCheckerConfig:
    def __init__(self, enable=True,
                 debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list


_checker_set_lists = False


def enable_tensor_checker(config: TensorCheckerConfig):
    global _checker_set_lists
    set_flags({"FLAGS_check_nan_inf": config.enable})
    set_flags({"FLAGS_check_nan_inf_level":
               0 if config.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT
               else 1})
    if config.checked_op_list is not None or \
            config.skipped_op_list is not None:
        set_checked_op_list(config.checked_op_list)
        set_skipped_op_list(config.skipped_op_list)
        _checker_set_lists = True


def disable_tensor_checker():
    global _checker_set_lists
    set_flags({"FLAGS_check_nan_inf": False})
    if _checker_set_lists:  # don't wipe lists set independently
        set_checked_op_list(None)
        set_skipped_op_list(None)
        _checker_set_lists = False


def check_numerics(tensor: Tensor, op_type: str = "", var_name: str = "",
                   debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    """Return (num_nan, num_inf, num_zero) stats; abort per mode."""
    a = tensor._data
    n_nan = int(jnp.isnan(a).sum())
    n_inf = int(jnp.isinf(a).sum())
    n_zero = int((a == 0).sum())
    if n_nan or n_inf:
        msg = (f"[check_numerics] op={op_type} var={var_name}: "
               f"{n_nan} NaN, {n_inf} Inf")
        if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
            raise FloatingPointError(msg)
        print("WARNING:", msg)
    return (Tensor(jnp.asarray(n_nan)), Tensor(jnp.asarray(n_inf)),
            Tensor(jnp.asarray(n_zero)))


def check_layer_numerics(func):
    """Decorator for Layer.forward: checks inputs/outputs for NaN/Inf
    (reference debugging.py:78). Tracer values (under jit/vjp tracing)
    pass through unchecked, like the apply_op-level _check_finite."""
    import functools
    import jax as _jax

    def _bad(a):
        return (not isinstance(a, _jax.core.Tracer)
                and jnp.issubdtype(a.dtype, jnp.floating)
                and not bool(jnp.isfinite(a).all()))

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        for name, a in list(enumerate(args)) + list(kwargs.items()):
            if isinstance(a, Tensor) and _bad(a._data):
                raise FloatingPointError(
                    f"NaN/Inf in input {name} of "
                    f"{type(self).__name__}.forward")
        out = func(self, *args, **kwargs)
        outs = out if isinstance(out, (tuple, list)) else [out]
        for o in outs:
            if isinstance(o, Tensor) and _bad(o._data):
                raise FloatingPointError(
                    f"NaN/Inf in output of {type(self).__name__}.forward")
        return out
    return wrapper


# ---------------------------------------------------------------------------
# operator stats collection (reference debugging.py:481 — per-op call
# counts split by output dtype, printed as a table)
# ---------------------------------------------------------------------------

_op_stats: Optional[dict] = None


def op_filtered(name: str) -> bool:
    """True when the checked/skipped op lists exclude this op (shared by
    operator-stats collection and the apply_op NaN/Inf checker)."""
    if _checked_ops is not None and name not in _checked_ops:
        return True
    return name in _skipped_ops


def _observe(name, tensors):
    if _op_stats is None or op_filtered(name) or not tensors:
        return
    # one count per op CALL (not per output); classify by the first
    # output's dtype — the op's compute dtype under AMP
    dt = getattr(tensors[0]._data.dtype, "name",
                 str(tensors[0]._data.dtype))
    idx = {"float16": 0, "bfloat16": 1, "float32": 2}.get(dt, 3)
    _op_stats[name][idx] += 1


def enable_operator_stats_collection() -> None:
    global _op_stats
    _op_stats = defaultdict(lambda: [0, 0, 0, 0])
    _tensor_mod._op_observer = _observe


def disable_operator_stats_collection() -> None:
    global _op_stats
    _tensor_mod._op_observer = None
    stats, _op_stats = _op_stats, None
    if stats:
        _print_operator_stats(stats)


def _print_operator_stats(stats) -> None:
    print("<{:-^120}>".format(" op list "))
    head = "{:<40} | {:<17} | {:<17} | {:<17} | {:<17}".format(
        "OP Type", "FP16 Calls", "BF16 Calls", "FP32 Calls", "Other Calls")
    print(head)
    for op, (f16, bf16, f32, other) in sorted(stats.items()):
        print("{:<40} | {:<17} | {:<17} | {:<17} | {:<17}".format(
            op, f16, bf16, f32, other))
    print("<{:-^120}>".format(" op count: " + str(len(stats)) + " "))


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def collect_operator_numerical_stats(tensor: Tensor):
    a = np.asarray(tensor._data, dtype=np.float64)
    return {"min": float(a.min()), "max": float(a.max()),
            "mean": float(a.mean()),
            "num_nan": int(np.isnan(a).sum()),
            "num_inf": int(np.isinf(a).sum())}


# ---------------------------------------------------------------------------
# accuracy comparison tooling
# ---------------------------------------------------------------------------

def accuracy_check(x, y, fn_name: str = "", rtol: float = 1e-5,
                   atol: float = 1e-8, equal_nan: bool = False):
    """In-graph tensor comparison (phi accuracy_check kernel,
    ops.yaml:31): returns a scalar bool Tensor; raises in eager mode when
    the tensors differ so acc-align runs fail loudly."""
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    ya = y._data if isinstance(y, Tensor) else jnp.asarray(y)
    ok = jnp.allclose(xa.astype(jnp.float32), ya.astype(jnp.float32),
                      rtol=rtol, atol=atol, equal_nan=equal_nan)
    import jax
    if not isinstance(ok, jax.core.Tracer) and not bool(ok):
        diff = float(jnp.abs(xa.astype(jnp.float32)
                             - ya.astype(jnp.float32)).max())
        raise AssertionError(
            f"accuracy_check failed for {fn_name!r}: max |diff|={diff:g} "
            f"(rtol={rtol}, atol={atol})")
    return Tensor(ok)


def save_tensor_stats(path: str, tag: str, tensors: dict) -> None:
    """Dump per-tensor numerical stats as jsonl for compare_accuracy."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, f"{tag}.jsonl"), "w") as f:
        for name, t in tensors.items():
            rec = {"name": name}
            rec.update(collect_operator_numerical_stats(
                t if isinstance(t, Tensor) else Tensor(jnp.asarray(t))))
            f.write(json.dumps(rec) + "\n")


def compare_accuracy(dump_path: str, another_dump_path: str,
                     output_filename: str = "compare.csv",
                     loss_scale: float = 1.0,
                     dump_all_tensors: bool = False) -> List[dict]:
    """Compare two run dumps written by save_tensor_stats (reference
    debugging.py compare_accuracy reads workerlog dumps and writes an
    excel sheet; here jsonl in → csv out). Returns the row dicts;
    dump_all_tensors additionally includes both runs' raw per-tensor
    stats (min/max/mean/nan/inf) in each row."""
    def load(path):
        recs = {}
        for fn in sorted(os.listdir(path)):
            if not fn.endswith(".jsonl"):
                continue
            with open(os.path.join(path, fn)) as f:
                for line in f:
                    r = json.loads(line)
                    recs[r["name"]] = r
        return recs

    a, b = load(dump_path), load(another_dump_path)
    rows = []
    stat_keys = ("min", "max", "mean", "num_nan", "num_inf")
    for name in sorted(set(a) | set(b)):
        ra, rb = a.get(name), b.get(name)
        row = {"name": name,
               "in_both": ra is not None and rb is not None}
        if ra and rb:
            row["mean_diff"] = abs(ra["mean"] - rb["mean"]) / loss_scale
            row["max_diff"] = abs(ra["max"] - rb["max"]) / loss_scale
            row["nan_mismatch"] = ra["num_nan"] != rb["num_nan"]
            row["inf_mismatch"] = ra["num_inf"] != rb["num_inf"]
        if dump_all_tensors:
            for tag, rec in (("a", ra), ("b", rb)):
                for kk in stat_keys:
                    row[f"{tag}_{kk}"] = rec.get(kk, "") if rec else ""
        rows.append(row)
    if output_filename:
        fields = ["name", "in_both", "mean_diff", "max_diff",
                  "nan_mismatch", "inf_mismatch"]
        if dump_all_tensors:
            fields += [f"{tag}_{kk}" for tag in ("a", "b")
                       for kk in stat_keys]
        with open(output_filename, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=fields)
            w.writeheader()
            for r in rows:
                w.writerow({k: r.get(k, "") for k in fields})
    return rows
