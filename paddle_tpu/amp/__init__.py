"""AMP (reference: python/paddle/amp/ — auto_cast.py:462 amp_guard, :1029
auto_cast, grad_scaler.py:657 GradScaler, amp_lists.py white/black lists).

TPU-native notes: bf16 is the native low-precision dtype (no loss scaling
strictly needed — GradScaler becomes a cheap pass-through that still
implements the full found_inf protocol for float16 parity). O1 casting
hooks the single ``apply_op`` dispatch point instead of per-op generated AMP
blocks (eager_gen.py:589).
"""
from .auto_cast import (auto_cast, amp_guard, decorate, amp_decorate,
                        is_float16_supported, is_bfloat16_supported,
                        WHITE_LIST, BLACK_LIST, amp_state)
from .grad_scaler import GradScaler, AmpScaler
from . import debugging  # noqa: F401

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler", "AmpScaler",
           "is_float16_supported", "is_bfloat16_supported", "debugging"]
