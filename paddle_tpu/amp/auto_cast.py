"""auto_cast / decorate — O1 (op-level autocast) and O2 (model cast).

Reference: python/paddle/amp/auto_cast.py:462 (amp_guard), :1029 (auto_cast);
op lists python/paddle/amp/amp_lists.py. The O1 cast hook lives in
framework.tensor.apply_op via ``maybe_autocast_inputs``.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional, Set

import jax.numpy as jnp

from ..framework.dtype import to_dtype
from ..framework.tensor import Tensor, no_grad

# ops whose inputs are cast to the low-precision dtype under O1
# (FP16_WHITE_LIST in amp_lists.py: matmul-class + conv-class)
WHITE_LIST: Set[str] = {
    "matmul", "bmm", "mm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "addmm", "sdpa", "flash_attention", "flash_attn_unpadded",
}

# ops forced to float32 under O1 (FP16_BLACK_LIST: numerically sensitive)
BLACK_LIST: Set[str] = {
    "exp", "log", "log2", "log10", "log1p", "pow", "square", "sqrt", "rsqrt",
    "softmax", "log_softmax", "cross_entropy", "nll_loss", "kl_div",
    "binary_cross_entropy", "bce_with_logits", "mse_loss", "l1_loss",
    "mean", "sum", "p_norm", "cumsum", "logsumexp", "erf", "erfinv",
    "layer_norm", "bn_stats", "batch_norm", "group_norm", "rms_norm",
    "softmax_with_cross_entropy", "sigmoid_focal_loss",
}

_state = threading.local()


class _AmpState:
    __slots__ = ("enable", "dtype", "level")

    def __init__(self, enable, dtype, level):
        self.enable = enable
        self.dtype = dtype
        self.level = level


def amp_state() -> Optional[_AmpState]:
    return getattr(_state, "amp", None)


def maybe_autocast_inputs(op_name: str, arrs):
    """Called by apply_op: cast input arrays per the amp level. O1 casts
    white-listed ops down / black-listed ops up; O2 casts EVERY op's fp32
    inputs down except the black list (reference amp_guard O2 semantics —
    params are already low precision via ``decorate``, masters stay fp32
    in the optimizer). Returns the (possibly) cast list."""
    st = amp_state()
    if st is None or not st.enable or st.level not in ("O1", "O2"):
        return arrs
    if op_name in BLACK_LIST:
        return [a.astype(jnp.float32)
                if hasattr(a, "dtype") and a.dtype in (jnp.float16,
                                                       jnp.bfloat16) else a
                for a in arrs]
    # explicit dtype conversion is the user's escape hatch out of the
    # autocast region — never intercept it (a cast-to-fp32 would
    # otherwise round-trip through the low dtype and truncate)
    if op_name == "cast":
        return arrs
    if st.level == "O2" or op_name in WHITE_LIST:
        tgt = st.dtype
        return [a.astype(tgt)
                if hasattr(a, "dtype") and a.dtype == jnp.float32 else a
                for a in arrs]
    return arrs


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast analog. Default low dtype on TPU is bfloat16."""
    if level not in ("O0", "O1", "O2"):
        raise ValueError(f"level must be O0/O1/O2, got {level}")
    added_w, added_b = set(), set()
    if custom_white_list:
        added_w = set(custom_white_list) - WHITE_LIST
        WHITE_LIST.update(added_w)
    if custom_black_list:
        added_b = set(custom_black_list) - BLACK_LIST
        BLACK_LIST.update(added_b)
    prev = amp_state()
    _state.amp = _AmpState(enable and level != "O0",
                           to_dtype(dtype).np_dtype, level)
    try:
        yield
    finally:
        _state.amp = prev
        WHITE_LIST.difference_update(added_w)
        BLACK_LIST.difference_update(added_b)


amp_guard = auto_cast


_FP32_KEEP_LAYERS = ("BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm",
                     "RMSNorm")


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False):
    """O2: cast model params to the low dtype, keeping norm layers fp32
    (reference auto_cast.py amp_decorate). Optimizer master weights are
    handled by the Adam-family `multi_precision` path."""
    if level == "O1":
        return (models, optimizers) if optimizers is not None else models
    nd = to_dtype(dtype).np_dtype
    model_list = models if isinstance(models, (list, tuple)) else [models]
    with no_grad():
        for model in model_list:
            for layer in model.sublayers(include_self=True):
                if any(k in type(layer).__name__ for k in _FP32_KEEP_LAYERS):
                    continue
                for p in layer._parameters.values():
                    if p is not None and p._data.dtype == jnp.float32:
                        p._data = p._data.astype(nd)
    if optimizers is not None:
        return models, optimizers
    return models


amp_decorate = decorate


def is_float16_supported(device=None) -> bool:
    return True


def is_bfloat16_supported(device=None) -> bool:
    return True
