"""Generic retry with exponential backoff + jitter, deadline-aware.

One policy object serves every transient-failure site in the framework
(TCPStore client ops, checkpoint shard I/O, watchdog heartbeats): it
classifies exceptions (``retry_on``), backs off exponentially with
seeded jitter, respects a per-call wall-clock budget (never sleeps past
the deadline), and publishes per-attempt metrics
(``ptpu_retry_attempts_total{op}`` / ``..._failures_total`` /
``..._exhausted_total``) so a flaky dependency is visible long before
it becomes an outage.

Clock and sleep are injectable, so tests drive the full
backoff/deadline logic without real waiting::

    policy = RetryPolicy(max_attempts=5, base_delay=0.05, seed=0,
                         sleep_fn=fake_sleep, time_fn=fake_clock)
    value = policy.call(store.get, "key", op="store.get")

``InjectedFault`` (resilience.faults) is retryable by default — fault
points exist precisely to prove these retry paths on CPU.
"""
from __future__ import annotations

import functools
import random
import time
from typing import Callable, Optional, Tuple, Type

from .faults import InjectedFault

__all__ = ["RetryError", "RetryPolicy", "RetryingStore"]


class RetryError(RuntimeError):
    """Raised when every attempt failed (or the deadline cut retries
    short); chains from the last underlying exception."""

    def __init__(self, op: str, attempts: int, last: BaseException,
                 reason: str = "attempts exhausted"):
        super().__init__(
            f"{op}: {reason} after {attempts} attempt(s); last error: "
            f"{type(last).__name__}: {last}")
        self.op = op
        self.attempts = attempts
        self.last = last


class RetryPolicy:
    """Exponential backoff with jitter; see module docstring.

    ``deadline`` is a per-call wall-clock budget in seconds (measured on
    ``time_fn``): an attempt whose backoff sleep would overrun it gives
    up immediately instead of sleeping into a guaranteed timeout.
    """

    def __init__(self, max_attempts: int = 4, base_delay: float = 0.05,
                 max_delay: float = 2.0, multiplier: float = 2.0,
                 jitter: float = 0.25,
                 deadline: Optional[float] = None,
                 retry_on: Tuple[Type[BaseException], ...] = (
                     ConnectionError, TimeoutError, OSError,
                     InjectedFault),
                 no_retry_on: Tuple[Type[BaseException], ...] = (),
                 sleep_fn: Callable[[float], None] = time.sleep,
                 time_fn: Callable[[], float] = time.monotonic,
                 seed: Optional[int] = None, registry=None):
        if max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.deadline = deadline
        self.retry_on = tuple(retry_on)
        # carve-outs win over retry_on: needed because the exception
        # tree overlaps (TimeoutError IS an OSError on 3.10+, and "key
        # not set" timeouts are answers, not faults)
        self.no_retry_on = tuple(no_retry_on)
        self.sleep = sleep_fn
        self.now = time_fn
        self._rng = random.Random(seed)
        self._registry = registry
        self._m_attempts = self._m_failures = self._m_exhausted = None

    def _metrics(self):
        if self._m_attempts is None:
            reg = self._registry
            if reg is None:
                from ..observability import default_registry
                reg = default_registry()
            self._m_attempts = reg.counter(
                "ptpu_retry_attempts_total",
                "retry-policy call attempts", labels=("op",))
            self._m_failures = reg.counter(
                "ptpu_retry_failures_total",
                "retryable attempt failures", labels=("op",))
            self._m_exhausted = reg.counter(
                "ptpu_retry_exhausted_total",
                "calls that gave up (attempts or deadline)",
                labels=("op",))
        return self._m_attempts, self._m_failures, self._m_exhausted

    def backoff(self, attempt: int) -> float:
        """Jittered delay after failed attempt ``attempt`` (1-based)."""
        d = min(self.max_delay,
                self.base_delay * self.multiplier ** (attempt - 1))
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, d)

    def call(self, fn: Callable, *args, op: Optional[str] = None,
             deadline: Optional[float] = None, **kwargs):
        """Run ``fn(*args, **kwargs)`` under the policy and return its
        value. Non-retryable exceptions propagate immediately."""
        op = op or getattr(fn, "__name__", "call")
        budget = self.deadline if deadline is None else deadline
        t0 = self.now()
        m_att, m_fail, m_exh = self._metrics()
        attempt = 0
        while True:
            attempt += 1
            m_att.labels(op=op).inc()
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                if self.no_retry_on and \
                        isinstance(e, self.no_retry_on):
                    raise
                m_fail.labels(op=op).inc()
                if attempt >= self.max_attempts:
                    m_exh.labels(op=op).inc()
                    raise RetryError(op, attempt, e) from e
                delay = self.backoff(attempt)
                if budget is not None and \
                        (self.now() - t0) + delay > budget:
                    m_exh.labels(op=op).inc()
                    raise RetryError(
                        op, attempt, e,
                        reason=f"deadline {budget}s would be exceeded"
                    ) from e
                self.sleep(delay)

    def wrap(self, fn: Callable, op: Optional[str] = None) -> Callable:
        """Decorator form of :meth:`call`."""
        op = op or getattr(fn, "__name__", "call")

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, op=op, **kwargs)

        return wrapped


class RetryingStore:
    """A store wrapper applying a RetryPolicy to the client ops.

    ``TimeoutError`` from ``get``/``wait`` is the store's legitimate
    "key not set yet" answer, NOT a transient fault — the default
    policy here retries only transport-level errors (ConnectionError /
    OSError / injected faults), so watchdog-style polling keeps its
    latency. Pass a custom policy to change the classification.
    """

    def __init__(self, store, policy: Optional[RetryPolicy] = None):
        self.store = store
        self.policy = policy or RetryPolicy(
            retry_on=(ConnectionError, OSError, InjectedFault),
            no_retry_on=(TimeoutError,))

    def set(self, key, value):
        return self.policy.call(self.store.set, key, value,
                                op="store.set")

    def get(self, key, timeout=None):
        return self.policy.call(self.store.get, key, timeout=timeout,
                                op="store.get")

    def add(self, key, delta=1):
        # NOT idempotent: a retry after a lost *response* double-counts.
        # Safe for the framework's uses (heartbeat counters, where only
        # "the value moved" matters); don't route exactly-once counters
        # through this wrapper.
        return self.policy.call(self.store.add, key, delta,
                                op="store.add")

    def wait(self, key, timeout=None):
        return self.policy.call(self.store.wait, key, timeout=timeout,
                                op="store.wait")

    def __getattr__(self, name):
        # everything else (world_size, barrier, close, ...) passes
        # through un-retried
        return getattr(self.store, name)
