"""Seeded chaos-soak scheduler: randomized fault schedules, checked
against the conservation invariants after every episode.

One episode = one seed. The seed deterministically samples a fault
schedule over the registered fault points (``faults.KNOWN_POINTS``)
*and* a workload, drives a full system episode — the serving engine
under Poisson arrivals with deadlines, cancels and ``recover()``, or a
:class:`~paddle_tpu.resilience.train_loop.ResilientTrainLoop` with
injected crashes, torn checkpoints, flaky stores and process
relaunches — and then audits every invariant in
``resilience/invariants.py``:

- request conservation (exactly-once delivery, via the engine's
  ``auditor`` hooks),
- greedy token identity against an uninjected replay of the same
  prompts,
- loss-trajectory continuity against an uninjected baseline run,
- checkpoint-generation monotonicity with torn-file tolerance,
- no leaked slots / queue entries / KV pages (paged-cache refcounts
  return to zero, including across mid-prefill faults on
  shared-prefix admissions) / pending save handles / non-daemon
  threads.

A violation is therefore a *seed*: re-running the same seed replays
the identical fault schedule and workload (virtual clocks, seeded
RNGs, no wall-clock anywhere), so every red episode is a one-line
reproducer. ``tests/test_chaos.py`` runs a fixed seed matrix in
tier-1 and pins seeds that catch the PR-3 deferred failure-path bug
classes; ``benchmarks/chaos_soak.py`` runs the open-ended soak under
a time/episode budget.

The training episode simulates its peers instead of spawning them:
:class:`ChaosStore` is a dict-backed TCPStore stand-in wired to the
SAME ``store.*`` fault points as the native client, the watchdog's
rank-1 peer heartbeats are replayed through that store, and the
``io.dataloader.worker`` point fires inside the step function the way
a dead worker process surfaces inside a real step. Everything runs
single-process on CPU.
"""
from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import faults
from .invariants import (ConservationLedger, checkpoint_monotonic_violations,
                         engine_leak_violations, frontdoor_leak_violations,
                         loss_trajectory_violations,
                         page_leak_violations, pending_save_violations,
                         router_leak_violations,
                         thread_leak_violations, timeline_violations,
                         token_prefix_violations)

__all__ = ["FaultArm", "EpisodeResult", "ChaosStore",
           "SERVING_SWEEP", "TRAINING_SWEEP", "FRONTDOOR_SWEEP",
           "CLUSTER_SWEEP", "CONTROL_SWEEP",
           "run_serving_episode", "run_training_episode",
           "run_frontdoor_episode", "run_cluster_episode",
           "run_episode"]

# the sweep partition: every KNOWN point is sampled by exactly one
# episode kind (tests assert the union covers the whole catalogue).
# Front-door episodes ALSO sample the serving points (the full stack
# includes the engines), but coverage of those is owned by the
# serving sweep.
SERVING_SWEEP = ("serving.step.decode", "serving.decode.verify",
                 "serving.decode.sharded",
                 "serving.step.prefill", "serving.prefill.paged",
                 "serving.prefill.chunk", "serving.kv.handoff",
                 "serving.kv.demote", "serving.kv.promote",
                 "serving.spec.draft", "serving.spec.resample")
FRONTDOOR_SWEEP = ("router.dispatch", "router.health_probe",
                   "frontdoor.stream_write",
                   "frontdoor.client_disconnect")
TRAINING_SWEEP = ("train.step", "io.dataloader.worker",
                  "checkpoint.shard_write", "checkpoint.commit",
                  "watchdog.beat",
                  "store.set", "store.get", "store.add", "store.wait")
# the RPC wire points live in distributed/_framing.py and fire in
# whichever process does the send/recv: armed client-side they are
# the network-partition kill kind of the cluster episodes. The auth
# point fires inside the handshake/per-frame MAC verification (a blip
# below the retry budget re-handshakes invisibly; past it, the replica
# partitions); the kv-wire point fires inside the cross-host handoff
# transport (armed in the SERVING episodes' disagg flavor, which owns
# the wire-handoff abort law); the weights point fires inside a
# worker's digest-verified fetch (serving/weight_store.py). The
# send/recv pair MUST stay first: the partition-kind draw indexes
# CLUSTER_SWEEP[0:2] and pre-fabric seeds are bit-identical.
CLUSTER_SWEEP = ("cluster.rpc.send", "cluster.rpc.recv",
                 "cluster.rpc.auth", "cluster.kv.wire",
                 "cluster.weights.fetch")
# control-plane actuator points (serving/control.py). Ownership:
# frontdoor episodes arm shed/affinity/scale (the controllers live on
# the front door + router there), serving episodes arm chunk (the
# budget controller lives on the engine). A fired control arm is
# CONTAINED by the Actuator — the one actuation is suppressed, the
# data plane keeps its last setting, admission fails open — so these
# arms certify that a sick control plane degrades the SLO, never the
# conservation laws.
CONTROL_SWEEP = ("control.shed", "control.chunk",
                 "control.affinity", "control.scale")


@dataclasses.dataclass
class FaultArm:
    """One sampled injection: fail ``times`` times after ``after``
    hits at ``point`` (the deterministic count-based grammar — finite
    budgets guarantee every episode terminates)."""
    point: str
    times: int
    after: int

    def arm(self) -> None:
        faults.inject(self.point, times=self.times, after=self.after)


@dataclasses.dataclass
class EpisodeResult:
    seed: int
    kind: str                     # "serving" | "training"
    violations: List[str]         # empty = every invariant held
    schedule: List[FaultArm]      # what the seed armed (reproducer)
    fired: Dict[str, int]         # faults that actually fired
    stats: Dict[str, object]

    @property
    def ok(self) -> bool:
        return not self.violations


# ---------------------------------------------------------------------------
# serving episodes
# ---------------------------------------------------------------------------

# fixed prompt pool + reference outputs, cached per process: the
# references ARE the uninjected replay (same engine, same greedy
# decode), computed once; greedy decoding is prefix-stable, so any
# episode request over pool prompt i must emit a prefix of _REFS[i].
# The model is deliberately minuscule (1 layer, d=32): every episode
# compiles its own engine programs, and the soak's value is in the
# failure bookkeeping, not the matmuls.
_MAX_LEN = 32
_MIN_BUCKET = 8
_REF_HORIZON = 8
_model = None
_draft_models: dict = {}
_refs: Optional[List[List[int]]] = None
_pool: Optional[List[np.ndarray]] = None


def _prompt_pool() -> List[np.ndarray]:
    global _pool
    if _pool is None:
        rng = np.random.RandomState(1234)
        _pool = [rng.randint(1, 96, (int(n),)).astype(np.int64)
                 for n in (3, 4, 5, 7, 9, 12)]
        # shared-prefix prompts (episodes run the PAGED engine with
        # page_size 8): one full-page hit on the 12-token prompt's
        # first page, and one mid-page hit that forces a COW — so
        # mid-prefill faults land on shared-prefix admissions too
        base = _pool[5]
        _pool.append(np.concatenate(
            [base[:8], rng.randint(1, 96, (3,))]).astype(np.int64))
        _pool.append(np.concatenate(
            [base[:6], rng.randint(1, 96, (1,))]).astype(np.int64))
        # repetitive prompts (periodic suffix / repeated token): the
        # SPECULATIVE episodes' n-gram draft proposer finds matches
        # here, so verify steps really accept multi-token runs — and
        # the pinned broken-acceptance seed really diverges
        pat = rng.randint(1, 96, (3,)).astype(np.int64)
        _pool.append(np.tile(pat, 4))                    # period 3
        _pool.append(np.full((10,), int(rng.randint(1, 96)),
                             np.int64))                  # period 1
    return _pool


def _serving_model():
    global _model
    if _model is None:
        import paddle_tpu as paddle
        from ..models.llama import LlamaForCausalLM, llama_tiny_config
        paddle.seed(0)
        _model = LlamaForCausalLM(llama_tiny_config(
            num_hidden_layers=1, hidden_size=32, intermediate_size=64,
            num_attention_heads=2, max_position_embeddings=_MAX_LEN))
        _model.eval()
    return _model


def _draft_serving_model(variant: str):
    """Cached draft models for the DRAFT-PROPOSER episode flavor.
    ``"same"`` is the target model itself (the oracle draft: every
    proposal accepted, the widest verify rows exercised); ``"other"``
    is an independently-seeded twin (disagreeing drafts: the
    rejection/partial-acceptance paths exercised). Both tiny — the
    soak's value is the bookkeeping, not the matmuls."""
    if variant == "same":
        return _serving_model()
    if variant not in _draft_models:
        import paddle_tpu as paddle
        from ..models.llama import LlamaForCausalLM, llama_tiny_config
        paddle.seed(7)
        m = LlamaForCausalLM(llama_tiny_config(
            num_hidden_layers=1, hidden_size=32, intermediate_size=64,
            num_attention_heads=2, max_position_embeddings=_MAX_LEN))
        m.eval()
        _draft_models[variant] = m
    return _draft_models[variant]


def _reference_outputs() -> List[List[int]]:
    """Uninjected greedy replay of every pool prompt (fault-free
    engine run), the token-identity baseline for all episodes."""
    global _refs
    if _refs is None:
        from ..observability import FlightRecorder, MetricRegistry
        from ..serving import ServingEngine
        faults.clear()
        eng = ServingEngine(_serving_model(), max_slots=2,
                            max_len=_MAX_LEN, min_bucket=_MIN_BUCKET,
                            registry=MetricRegistry(),
                            flight_recorder=FlightRecorder(capacity=4))
        reqs = [eng.submit(p, max_new_tokens=_REF_HORIZON)
                for p in _prompt_pool()]
        eng.run()
        _refs = [list(r.out_tokens) for r in reqs]
    return _refs


def _sample_arms(rng, specs) -> List[FaultArm]:
    """``specs``: (point, probability, times_range, after_range)."""
    arms = []
    for point, prob, (t0, t1), (a0, a1) in specs:
        if rng.random() < prob:
            arms.append(FaultArm(point, times=int(rng.randint(t0, t1)),
                                 after=int(rng.randint(a0, a1))))
    return arms


def run_serving_episode(seed: int, max_iters: int = 300,
                        mesh_flavor: Optional[str] = None,
                        watchtower: bool = False,
                        arm_faults: bool = True) \
        -> EpisodeResult:
    """One seeded serving episode: Poisson arrivals over the fixed
    prompt pool with sampled deadlines/cancels, decode/prefill faults
    (donated-pool and CPU flavors), ``recover()`` after broken steps,
    and a final ``drain()`` — possibly itself under fire. Every
    invariant is audited at the end.

    ``mesh_flavor`` pins the engine's mesh layout: ``"local"``
    (single-chip), ``"tp"`` (TP=2 over the emulated mesh) or
    ``"disagg"`` (2 prefill + 2 decode devices, KV handoff path).
    None samples it — from a SEPARATE rng stream, so every pre-mesh
    seed's fault schedule and workload stay bit-identical. Mesh
    flavors degrade to "local" when the process has too few (virtual)
    devices; mesh episodes are audited against the SAME single-chip
    reference outputs — cross-flavor token identity IS the
    tensor-parallel correctness law.

    ``watchtower=True`` attaches an observability watchtower to the
    episode's registry + virtual clock (polled every iteration,
    flushed at quiesce) and reports its incidents in the episode
    stats. ``arm_faults=False`` runs the SAME seed — every rng draw
    happens, the schedule is built, the workload is bit-identical —
    but no arm is ever armed: the clean band the watchtower's
    false-positive floor is certified against."""
    from ..observability import FlightRecorder, MetricRegistry
    from ..serving import ServingEngine

    model = _serving_model()
    refs = _reference_outputs()
    pool = _prompt_pool()
    faults.clear()
    faults.reset_counts()
    rng = np.random.RandomState(seed)
    ledger = ConservationLedger()
    clock = {"t": 0.0}
    max_slots = int(rng.randint(1, 4))
    donate = bool(rng.randint(0, 2))    # TPU-like donated pools or CPU
    # half the episodes run the SPECULATIVE engine: n-gram drafts +
    # the widened verify program, audited against the SAME
    # non-speculative reference outputs — the token-identity law IS
    # the speculative-correctness law
    speculative = bool(rng.randint(0, 2))
    # paged geometry: page_size 8 (4 pages per full-length row) with a
    # sampled pool budget — small budgets exercise page-gated
    # admission and queue growth under oversubscription
    num_pages = int(rng.randint(_MAX_LEN // 8 + 1,
                                max_slots * (_MAX_LEN // 8) + 2))
    spec_kw = {"speculative": True, "spec_k": 4} if speculative else {}
    import jax
    rng2 = np.random.RandomState(770000 + seed)
    r_mesh = rng2.random()
    if mesh_flavor is None:
        if jax.device_count() >= 4 and r_mesh < 0.18:
            mesh_flavor = "disagg"
        elif jax.device_count() >= 2 and r_mesh < 0.38:
            mesh_flavor = "tp"
        else:
            mesh_flavor = "local"
    elif jax.device_count() < (4 if mesh_flavor == "disagg" else 2):
        # a PINNED flavor degrades too (not just the sampled path):
        # an image without the virtual-device emulation runs the
        # episode single-chip instead of crashing mid-matrix — the
        # coverage-floor test guards against this going vacuous
        mesh_flavor = "local"
    mesh_kw = {}
    if mesh_flavor == "tp":
        from ..distributed import ProcessMesh
        mesh_kw = {"mesh": ProcessMesh(np.arange(2), ["model"])}
    elif mesh_flavor == "disagg":
        from ..distributed import ProcessMesh
        mesh_kw = {"mesh": ProcessMesh(np.arange(4), ["model"]),
                   "prefill_devices": 2}
    # chunked prefill, drawn from a THIRD rng stream (same reason as
    # the mesh flavor: every pre-chunk seed's fault schedule, mesh
    # draw and workload stay bit-identical). Biased toward None so
    # most of the historical seed universe keeps exercising the
    # monolithic prefill path.
    rng3 = np.random.RandomState(880000 + seed)
    prefill_chunk = [None, None, None, 8, 16][int(rng3.randint(0, 5))]
    chunk_kw = {} if prefill_chunk is None \
        else {"prefill_chunk": prefill_chunk,
              "admission_lookahead": int(rng3.randint(0, 3))}
    # KV host tier, drawn from a FOURTH rng stream (same bit-identity
    # reasoning: every pre-tier seed's fault schedule, mesh/chunk
    # draws and workload are untouched). Draws are UNCONDITIONAL so
    # the stream stays aligned whatever the flavor; the tier only
    # applies on single-chip engines (mesh + tier raises by design).
    # Host-RAM only — the disk store's fault story is owned by
    # tests/test_kv_tier.py, and chaos must not litter the filesystem.
    rng4 = np.random.RandomState(990000 + seed)
    tiered_draw = rng4.random() < 0.45
    tier_cap = int(rng4.randint(2, 16))
    tier_unbounded = rng4.random() < 0.35
    # tier-on episodes squeeze the device pool down near the single-
    # request floor (draw unconditional, applied only with the tier):
    # at the sampled budgets above the pool almost never reclaims, so
    # without this clamp the demote/promote regime would soak green by
    # vacuity — zero demotions, arms never reached
    tier_pages = int(rng4.randint(_MAX_LEN // 8 + 1, _MAX_LEN // 8 + 4))
    tier_kw = {}
    if tiered_draw and mesh_flavor == "local":
        tier_kw = {"kv_host_tier": True,
                   "host_tier_pages": None if tier_unbounded
                   else tier_cap}
        num_pages = min(num_pages, tier_pages)
    # cross-host KV wire, drawn from a FIFTH rng stream (same
    # bit-identity reasoning as the mesh/chunk/tier streams): disagg
    # episodes sometimes route every prefill->decode handoff through
    # the real-socket transport (serving/kv_wire.py), so the staged
    # abort contract is certified with actual bytes on an actual wire.
    # Every draw below is UNCONDITIONAL so the stream stays aligned
    # whatever the flavor; the transport only applies on disagg.
    rng5 = np.random.RandomState(1100000 + seed)
    wire_draw = rng5.random() < 0.6
    wire_mode = rng5.random()        # <0.45 blip, <0.75 fatal arm
    wire_blip_times = int(rng5.randint(1, 3))   # < the 3-attempt budget
    wire_fatal_times = int(rng5.randint(4, 7))  # > it: the abort path
    wire_after = int(rng5.randint(0, 6))
    wire_transport = None
    wire_kw = {}
    if wire_draw and mesh_flavor == "disagg":
        from ..serving.kv_wire import LoopbackKVTransport
        wire_transport = LoopbackKVTransport(secret=b"chaos-kv-wire")
        wire_kw = {"kv_transport": wire_transport}
    # speculation v2, drawn from a SIXTH rng stream (same bit-identity
    # reasoning as the mesh/chunk/tier/wire streams — every pre-spec-v2
    # seed's fault schedule and workload are untouched). Draws are
    # UNCONDITIONAL so the stream stays aligned whatever the flavors;
    # they only apply on speculative episodes. Flavors: the DRAFT-MODEL
    # proposer (oracle "same" twin for wide acceptance, independently-
    # seeded "other" twin for rejection pressure), SAMPLED acceptance
    # (some requests carry temperature>0 — those are audited for
    # conservation/leaks but NOT token identity, which is a greedy
    # law), and the accept-rate TUNER (gating decisions under fire
    # must stay replayable: pure counters, no RNG).
    rng6 = np.random.RandomState(1210000 + seed)
    r_draftp = rng6.random()            # < 0.5 -> draft proposer
    draft_other = rng6.random() < 0.5   # disagreeing vs oracle draft
    r_sampled = rng6.random()           # < 0.35 -> sampled acceptance
    r_tune = rng6.random()              # < 0.4 -> tuner on
    sampled_flags = rng6.random(16) < 0.4   # per-submit-order flags
    r_arm_draft, t_arm_draft, a_arm_draft = (rng6.random(),
                                             int(rng6.randint(1, 3)),
                                             int(rng6.randint(0, 8)))
    r_arm_res, t_arm_res, a_arm_res = (rng6.random(),
                                       int(rng6.randint(1, 3)),
                                       int(rng6.randint(0, 6)))
    spec_proposer_kind = "ngram"
    spec_sampled_on = False
    if speculative:
        if r_draftp < 0.5:
            spec_proposer_kind = "draft"
            spec_kw["spec_proposer"] = "draft"
            spec_kw["draft_model"] = _draft_serving_model(
                "other" if draft_other else "same")
        if r_sampled < 0.35:
            spec_sampled_on = True
            spec_kw["spec_sampled"] = True
        if r_tune < 0.4:
            spec_kw["spec_tune"] = True
    # adaptive chunk budget, drawn from a SEVENTH rng stream (same
    # bit-identity reasoning as streams 2-6: every pre-control seed's
    # fault schedule and workload stay untouched). Draws are
    # UNCONDITIONAL so the stream stays aligned; the controller only
    # applies on chunked engines. Control-on episodes also append an
    # admission BURST (drawn here) so the queue-depth signal really
    # crosses the raise threshold — without it the adaptation
    # coverage floor would go green by vacuity.
    rng7 = np.random.RandomState(1320000 + seed)
    ctl_draw = rng7.random() < 0.65
    ctl_raise = float(rng7.randint(2, 5))
    r_arm_chunk, t_arm_chunk, a_arm_chunk = (rng7.random(),
                                             int(rng7.randint(1, 3)),
                                             int(rng7.randint(0, 4)))
    ctl_burst_t0 = float(rng7.randint(1, 3))
    n_ctl_burst = int(rng7.randint(4, 8))
    ctl_burst_dt = rng7.exponential(0.3, 8)
    ctl_burst_idx = rng7.randint(0, len(pool), 8)
    ctl_burst_new = rng7.randint(2, 6, 8)
    registry = MetricRegistry()
    chunk_control = None
    if ctl_draw and prefill_chunk is not None:
        from ..serving.control import Actuator, ChunkBudgetController
        chunk_control = ChunkBudgetController(
            raise_depth=ctl_raise, lower_depth=0.5, dwell=2,
            mults=(1, 2, 4),
            actuator=Actuator(window=8, registry=registry),
            registry=registry)
        chunk_kw["chunk_control"] = chunk_control
    eng = ServingEngine(model, max_slots=max_slots, max_len=_MAX_LEN,
                        min_bucket=_MIN_BUCKET,
                        page_size=8, num_pages=num_pages,
                        time_fn=lambda: clock["t"],
                        registry=registry,
                        flight_recorder=FlightRecorder(capacity=8),
                        auditor=ledger, **spec_kw, **mesh_kw,
                        **chunk_kw, **tier_kw, **wire_kw)
    if donate:
        eng._donate = lambda: (5, 6)
    wt = None
    if watchtower:
        wt = _serving_watchtower(registry, clock)
        wt.attach_engine(eng)

    n_req = int(rng.randint(4, 9))
    plan = []                 # (arrival_t, pool_idx, max_new, deadline)
    t = 0.0
    for _ in range(n_req):
        t += float(rng.exponential(1.5))
        # 1-token requests finish AT prefill — the admission-batch
        # finisher that a later prefill fault in the same step
        # strands; short deadlines expire queued/in-flight requests
        # in the same steps other faults land in
        max_new = 1 if rng.random() < 0.25 \
            else int(rng.randint(2, _REF_HORIZON + 1))
        plan.append((t, int(rng.randint(0, len(pool))), max_new,
                     float(rng.randint(2, 18))
                     if rng.random() < 0.45 else None))
    # tier-on episodes append a demote/promote duty cycle (drawn
    # UNCONDITIONALLY from rng4, applied only with the tier, so every
    # other seed's workload stays bit-identical): shared-prefix
    # requests around the pool[5] radix family alternating with
    # disjoint long prompts. Under the clamped pool this cycles pages
    # device -> host -> device — pressure the sampled arrivals almost
    # never produce, without which the demote/promote arms (and the
    # coverage floors over them) would go green by vacuity.
    # every rng4 draw below happens even when the value is then capped
    # or the request dropped — the stream position (and with it every
    # later arm draw) must not depend on the caps
    n_tier_req = int(rng4.randint(4, 8))
    t_tier = t
    tier_plan = []
    for i in range(n_tier_req):
        t_tier += float(rng4.exponential(1.5))
        idx = (5, 6)[int(rng4.randint(0, 2))] if i % 2 == 0 \
            else (4, 8, 9)[int(rng4.randint(0, 3))]
        mn = min(int(rng4.randint(2, _REF_HORIZON + 1)), 4)
        if i < 5:      # cap the executed cycle; tier-1 runtime budget
            tier_plan.append((t_tier, idx, mn, None))
    if tier_kw:
        plan.extend(tier_plan)
    # control-on chunked episodes splice in the admission burst drawn
    # from rng7 above (near-simultaneous arrivals early in the trace)
    # and re-sort by arrival; with the controller off the plan is
    # byte-for-byte the historical one
    if chunk_control is not None:
        tb = ctl_burst_t0
        for k in range(n_ctl_burst):
            tb += float(ctl_burst_dt[k])
            plan.append((tb, int(ctl_burst_idx[k]),
                         int(ctl_burst_new[k]), None))
        plan.sort(key=lambda e: e[0])
    cancels = []              # (submit order, loop iteration)
    if rng.random() < 0.4:
        cancels.append((int(rng.randint(0, n_req)),
                        int(rng.randint(1, 12))))
    schedule = _sample_arms(rng, [
        ("serving.step.decode", 0.6, (1, 3), (0, 8)),
        # mid-VERIFY-step kill (speculative episodes only reach it):
        # drafts built and speculative pages claimed — recovery must
        # replay token-identically and the rollback must leak nothing
        ("serving.decode.verify", 0.5, (1, 3), (0, 8)),
        ("serving.step.prefill", 0.5, (1, 3), (0, 8)),
        # mid-prefill on the paged cache: pages already claimed, so
        # the abort path (refcount unwind) is what's under fire —
        # including on shared-prefix admissions from the pool
        ("serving.prefill.paged", 0.4, (1, 3), (0, 8)),
    ])
    # mesh-only kill arms, drawn from the separate rng2 stream (same
    # reason as the flavor itself: pre-mesh seeds stay bit-identical):
    # the sharded-decode point fires right before the TP program, the
    # handoff point mid-handoff — KV computed on the prefill group,
    # not yet installed on the decode pool
    if mesh_flavor != "local" and rng2.random() < 0.5:
        schedule.append(FaultArm("serving.decode.sharded",
                                 times=int(rng2.randint(1, 3)),
                                 after=int(rng2.randint(0, 8))))
    if mesh_flavor == "disagg" and rng2.random() < 0.6:
        schedule.append(FaultArm("serving.kv.handoff",
                                 times=int(rng2.randint(1, 3)),
                                 after=int(rng2.randint(0, 6))))
    # chunk-boundary kill arm, drawn from the rng3 stream that owns
    # chunked-prefill sampling: fires between chunks of a PREFILLING
    # request — slot leased, pages claimed, part of the prompt
    # written — the unwind + requeue + re-chunk path is under fire
    if prefill_chunk is not None and rng3.random() < 0.55:
        schedule.append(FaultArm("serving.prefill.chunk",
                                 times=int(rng3.randint(1, 3)),
                                 after=int(rng3.randint(0, 6))))
    # chunk-budget actuator arm, from the rng7 stream that owns the
    # controller draw: fires inside the Actuator as the controller
    # tries to move the budget multiplier — containment means the
    # budget keeps its last value (fail-static) and the step proceeds
    if chunk_control is not None and r_arm_chunk < 0.55:
        schedule.append(FaultArm("control.chunk",
                                 times=t_arm_chunk,
                                 after=a_arm_chunk))
    # tier kill arms, from the rng4 stream that owns the tier draw
    # (draws unconditional, armed only when the tier is actually on):
    # demote fires before either tier mutates — the reclaim falls back
    # to destroy; promote fires with dst pages claimed and the request
    # staged — the abort path must return pages AND tier pins
    r_demote, t_demote, a_demote = (rng4.random(),
                                    int(rng4.randint(1, 3)),
                                    int(rng4.randint(0, 7)))
    r_promote, t_promote, a_promote = (rng4.random(),
                                       int(rng4.randint(1, 3)),
                                       int(rng4.randint(0, 5)))
    if tier_kw:
        if r_demote < 0.5:
            schedule.append(FaultArm("serving.kv.demote",
                                     times=t_demote, after=a_demote))
        if r_promote < 0.5:
            schedule.append(FaultArm("serving.kv.promote",
                                     times=t_promote, after=a_promote))
    # wire arm, from the rng5 stream that owns the transport draw
    # (draws above are unconditional; armed only when the wire is on):
    # a blip heals inside the transport's retry budget — token-
    # identically; a fatal arm outlasts it and must surface through
    # _kv_handoff's staged abort (pages returned, request requeued,
    # the prefill replayed — never a silent half-handoff)
    if wire_kw and wire_mode < 0.75:
        schedule.append(FaultArm(
            "cluster.kv.wire",
            times=(wire_blip_times if wire_mode < 0.45
                   else wire_fatal_times),
            after=wire_after))
    # speculation arms, from the rng6 stream that owns the spec-v2
    # flavor draws (draws above are unconditional; armed only when the
    # point is reachable): the draft point fires mid-proposal — the
    # containment law says the row degrades to k=1 THAT step (draft
    # state unwound, step still succeeds, token identity holds); the
    # resample point fires between first rejection and the residual
    # draw — verified tokens already delivered, so the unwind must
    # roll speculative pages back without double-emitting
    if speculative and r_arm_draft < 0.55:
        schedule.append(FaultArm("serving.spec.draft",
                                 times=t_arm_draft,
                                 after=a_arm_draft))
    if speculative and spec_sampled_on and r_arm_res < 0.5:
        schedule.append(FaultArm("serving.spec.resample",
                                 times=t_arm_res, after=a_arm_res))
    # shutdown chaos: half the episodes stop serving mid-trace and
    # drain() with the queue and slots still loaded — optionally with
    # one more decode fault armed right before the drain, the
    # mid-drain-failure regime drain() must survive without losing
    # its already-finished results
    shutdown_iter = int(rng.randint(2, 10)) \
        if rng.random() < 0.5 else None
    drain_arm = None
    if rng.random() < 0.5:
        drain_arm = FaultArm("serving.step.decode", times=1,
                             after=int(rng.randint(0, 3)))
        schedule = schedule + [drain_arm]
    if arm_faults:
        for arm in schedule:
            if arm is not drain_arm:
                arm.arm()

    violations: List[str] = []
    submitted: List[Tuple[object, int]] = []

    def _submit(pi, mn, dl):
        # sampled-acceptance episodes mark some requests (by submit
        # order, flags pre-drawn from rng6) temperature>0 with a
        # PINNED per-request seed: the run stays replayable, and the
        # greedy majority keeps the token-identity audit non-vacuous
        samp = None
        order = len(submitted)
        if spec_sampled_on and order < len(sampled_flags) \
                and sampled_flags[order]:
            from ..serving.sampling import SamplingParams
            samp = SamplingParams(temperature=0.8, top_k=8,
                                  seed=13579 + 1000 * seed + order)
        submitted.append((eng.submit(pool[pi], max_new_tokens=mn,
                                     deadline_s=dl, sampling=samp),
                          pi))
    recoveries = 0
    steps_ok = 0
    i = 0
    iters = 0
    try:
        while i < len(plan) or eng.has_work():
            iters += 1
            if iters > max_iters:
                violations.append(
                    f"episode did not quiesce within {max_iters} "
                    f"iterations")
                break
            if shutdown_iter is not None and iters >= shutdown_iter:
                # early shutdown: submit whatever the trace still owes
                # (so the drain inherits a loaded queue), then fall
                # through to drain()
                while i < len(plan):
                    _, pi, mn, dl = plan[i]
                    _submit(pi, mn, dl)
                    i += 1
                break
            clock["t"] += 1.0
            while i < len(plan) and plan[i][0] <= clock["t"]:
                _, pi, mn, dl = plan[i]
                _submit(pi, mn, dl)
                i += 1
            for order, at_iter in cancels:
                if at_iter == iters and order < len(submitted):
                    eng.cancel(submitted[order][0])
            if wt is not None:
                wt.poll()
            if not eng.has_work():
                continue
            try:
                eng.step()
                steps_ok += 1
            except Exception:
                # a broken engine (donated pools) needs recover() —
                # which may itself fault and is simply retried; a
                # non-broken fault left the request re-queued and the
                # next loop pass retries the step
                attempts = 0
                while eng._broken:
                    attempts += 1
                    if attempts > 10:
                        violations.append(
                            "recover() did not converge within 10 "
                            "attempts")
                        return _serving_result(
                            seed, violations, schedule, ledger,
                            submitted, refs, eng, recoveries,
                            steps_ok, wt)
                    try:
                        eng.recover()
                        recoveries += 1
                    except Exception:
                        continue
        if drain_arm is not None and arm_faults:
            drain_arm.arm()
        eng.drain()
    except Exception as e:  # noqa: BLE001 — any escape breaks the
        violations.append(  # "drain()/step() never strand work" law
            f"episode escaped with {type(e).__name__}: {e}")
    return _serving_result(seed, violations, schedule, ledger,
                           submitted, refs, eng, recoveries, steps_ok,
                           wt)


def _serving_watchtower(registry, clock):
    """The watchtower configuration the serving chaos band certifies:
    burn objectives in VIRTUAL seconds with thresholds far above what
    any clean episode produces (a clean 25-seed band must raise
    exactly zero incidents), the orphan detector on (a clean episode
    must never lose a request the metrics ledger still tracks), and
    the wall-clock-shaped detectors (stall, heartbeat, EWMA streams)
    off — an iteration-granular virtual clock freeze-frames between
    polls, which those detectors would misread as outages. They are
    certified synthetically in tests/test_watchtower.py instead."""
    from ..observability.watchtower import SLOObjective, Watchtower
    objectives = (
        SLOObjective("ttft_p50_virtual", threshold_s=120.0,
                     objective=0.5,
                     family="ptpu_serving_ttft_seconds",
                     phase="queue", fast_window_s=30.0,
                     slow_window_s=300.0),
        SLOObjective("queue_wait_p50_virtual", threshold_s=120.0,
                     objective=0.5,
                     family="ptpu_serving_queue_wait_seconds",
                     phase="queue", fast_window_s=30.0,
                     slow_window_s=300.0),
    )
    return Watchtower(registry=registry, objectives=objectives,
                      time_fn=lambda: clock["t"],
                      eval_interval_s=2.0, dedup_window_s=1e9,
                      stall_after_s=None, heartbeat_max_age_s=None,
                      anomaly_streams=False)


def _serving_result(seed, violations, schedule, ledger, submitted,
                    refs, eng, recoveries, steps_ok,
                    wt=None) -> EpisodeResult:
    if wt is not None:
        # two forced evaluations at quiesce: the orphan detector
        # requires two consecutive sightings, so a request dropped on
        # the episode's final iteration is still confirmed
        wt.flush()
        wt.flush()
    # wire teardown: the transport's server thread and sockets die
    # with the episode (both result paths funnel through here)
    wire_shipped = 0
    transport = getattr(eng, "kv_transport", None)
    if transport is not None:
        wire_shipped = int(getattr(transport, "shipped", 0))
        try:
            transport.close()
        except Exception:
            pass
    fired = faults.fired()
    faults.clear()
    violations = list(violations)
    violations += ledger.violations()
    violations += engine_leak_violations(eng)
    violations += page_leak_violations(eng)
    # token identity is a GREEDY law: sampled requests (temperature>0,
    # the sampled-acceptance episodes) draw from their private rng
    # streams and legitimately diverge from the greedy references —
    # they stay in the conservation/leak audits above, just not here
    violations += token_prefix_violations(
        (req, refs[pi]) for req, pi in submitted
        if req.sampling.temperature <= 0)
    return EpisodeResult(
        seed=seed, kind="serving", violations=violations,
        schedule=schedule, fired=fired,
        stats={"requests": len(submitted), "recoveries": recoveries,
               "steps": steps_ok,
               "donate": eng._donate() != (),
               "mesh": ("disagg" if eng.meshctx is not None
                        and eng.meshctx.disaggregated
                        else "tp" if eng.meshctx is not None
                        else "local"),
               "tp": eng.meshctx.tp if eng.meshctx is not None else 0,
               "speculative": eng.speculative,
               "spec_emitted": (eng._spec["emitted"]
                                if eng.speculative else 0),
               "spec_accepted_drafts": (
                   eng._spec["accepted_draft_tokens"]
                   if eng.speculative else 0),
               "spec_proposer": getattr(eng, "spec_proposer", None),
               "spec_sampled": getattr(eng, "spec_sampled", False),
               "spec_tuned": getattr(eng, "_tuner", None) is not None,
               "spec_draft_faults": (eng._spec["draft_faults"]
                                     if eng.speculative else 0),
               "spec_resamples": (eng._spec["resamples"]
                                  if eng.speculative else 0),
               "prefill_chunk": eng.prefill_chunk,
               "chunk_ctl": getattr(eng, "chunk_control", None)
               is not None,
               "chunk_adaptations": (
                   eng.chunk_control.adaptations
                   if getattr(eng, "chunk_control", None) is not None
                   else 0),
               "max_slots": eng.max_slots,
               "num_pages": eng.cache.num_pages,
               "prefix_hit_tokens": eng.cache.prefix_hit_tokens,
               "cow_copies": eng.cache.cow_copies,
               "kv_tiered": getattr(eng, "_kv_tier", None) is not None,
               "demotions": getattr(eng.cache, "demotions", 0),
               "promotions": getattr(eng.cache, "promotions", 0),
               "kv_wired": transport is not None,
               "wire_handoffs": wire_shipped,
               "incidents": (0 if wt is None
                             else len(wt.incidents())),
               "incident_kinds": sorted(
                   {(i.kind, i.phase) for i in wt.incidents()})
               if wt is not None else []})


# ---------------------------------------------------------------------------
# front-door episodes: replica kills through the full client stack
# ---------------------------------------------------------------------------

def run_frontdoor_episode(seed: int, max_iters: int = 300) \
        -> EpisodeResult:
    """One seeded FRONT-DOOR episode: Poisson client arrivals (token
    streams, tenants with sampled rate limits / in-flight caps,
    deadlines, explicit cancels and disconnects) through a
    :class:`~paddle_tpu.serving.frontdoor.FrontDoor` over a
    :class:`~paddle_tpu.serving.router.ReplicaRouter` of 2–3 engine
    replicas — under decode/prefill faults on the replicas,
    dispatch/probe/stream faults on the router and front door, and
    WHOLE-REPLICA KILLS: flag kills between steps and mid-step kills
    (a :class:`ReplicaDead` raised from inside a prefill or decode, so
    death lands mid-prefill and mid-stream). The conservation ledger
    is mounted at the front door, so exactly-once delivery and the
    admission (attempt = accept|reject) law are audited END-TO-END
    through the router, plus token identity vs the uninjected replay,
    stream consistency (what each connected client saw matches the
    request's terminal state), and router/front-door/page leaks."""
    from ..observability import FlightRecorder, MetricRegistry
    from ..serving import (FrontDoor, ClientStream, ReplicaDead,
                           ReplicaRouter, ServingEngine, ServingError,
                           Shed, TenantPolicy)

    model = _serving_model()
    refs = _reference_outputs()
    pool = _prompt_pool()
    faults.clear()
    faults.reset_counts()
    rng = np.random.RandomState(seed)
    ledger = ConservationLedger()
    clock = {"t": 0.0}
    n_replicas = int(rng.randint(2, 4))
    engines = []
    for _ in range(n_replicas):
        max_slots = int(rng.randint(1, 3))
        num_pages = int(rng.randint(_MAX_LEN // 8 + 1,
                                    max_slots * (_MAX_LEN // 8) + 2))
        eng = ServingEngine(model, max_slots=max_slots,
                            max_len=_MAX_LEN, min_bucket=_MIN_BUCKET,
                            page_size=8, num_pages=num_pages,
                            time_fn=lambda: clock["t"],
                            registry=MetricRegistry(),
                            flight_recorder=FlightRecorder(capacity=8))
        if rng.randint(0, 2):           # TPU-like donated pools
            eng._donate = lambda: (5, 6)
        engines.append(eng)
    router = ReplicaRouter(engines, registry=MetricRegistry(),
                           flight_recorder=FlightRecorder(capacity=8))
    tenants = {}
    if rng.random() < 0.5:
        # one throttled tenant so typed rejections flow through the
        # admission side of the ledger
        tenants["b"] = TenantPolicy(
            rate_qps=float(rng.randint(1, 4)) / 4.0, burst=2,
            max_inflight=int(rng.randint(1, 4)))
    # self-driving control plane, drawn from a SEVENTH rng stream
    # (same bit-identity reasoning as the serving streams 2-6: every
    # pre-control seed's fault schedule and workload stay untouched;
    # draws are UNCONDITIONAL, applied only when the control draw is
    # on). Control-on episodes run brownout shedding over priority
    # tiers, prefix-affinity dispatch and router autoscaling, plus an
    # OVERLOAD burst of unthrottled tiered traffic so the brownout
    # really trips — the graceful-degradation law (shed rate is
    # monotone in tier, tier 0 never shed) is asserted below whenever
    # anything was shed.
    rng7 = np.random.RandomState(1320000 + seed)
    control_on = rng7.random() < 0.6
    affinity_on = rng7.random() < 0.7
    autoscale_on = rng7.random() < 0.6
    enter_depth = float(rng7.randint(3, 6))
    up_pressure = float(rng7.randint(2, 4))
    burst_t0 = float(rng7.randint(1, 4))
    n_burst = int(rng7.randint(8, 13))
    # leading edge near-simultaneous (trips the brownout), tail spread
    # over several virtual seconds (lands on a HOT brownout and gets
    # shed — dwell means the level only rises a couple of pumps after
    # the front of the burst is already in the queues)
    burst_dt = rng7.exponential(0.7, 12)
    burst_dt[:4] = burst_dt[:4] * 0.1
    burst_idx = rng7.randint(0, len(pool), 12)
    burst_affin = rng7.random(12) < 0.5   # bias to the radix family
    burst_new = rng7.randint(2, 6, 12)
    r_arm_shed, t_arm_shed, a_arm_shed = (rng7.random(),
                                          int(rng7.randint(1, 3)),
                                          int(rng7.randint(0, 6)))
    r_arm_aff, t_arm_aff, a_arm_aff = (rng7.random(),
                                       int(rng7.randint(1, 3)),
                                       int(rng7.randint(0, 6)))
    r_arm_scale, a_arm_scale = (rng7.random(),
                                int(rng7.randint(0, 2)))
    control = None
    if control_on:
        from ..serving.control import (Actuator, BrownoutController,
                                       ControlPlane,
                                       PrefixAffinityPolicy,
                                       ReplicaAutoscaler)
        creg = MetricRegistry()
        act = Actuator(window=8, registry=creg)

        def _spawn_engine():
            return ServingEngine(
                model, max_slots=2, max_len=_MAX_LEN,
                min_bucket=_MIN_BUCKET, page_size=8,
                num_pages=_MAX_LEN // 8 + 2,
                time_fn=lambda: clock["t"],
                registry=MetricRegistry(),
                flight_recorder=FlightRecorder(capacity=8))

        aff = PrefixAffinityPolicy(min_tokens=8, actuator=act,
                                   registry=creg) \
            if affinity_on else None
        control = ControlPlane(
            brownout=BrownoutController(
                tiers=3, enter_depth=enter_depth, exit_depth=1.0,
                enter_burn=6.0, exit_burn=1.0, dwell=2,
                registry=creg),
            affinity=aff,
            autoscaler=ReplicaAutoscaler(
                min_replicas=1, max_replicas=n_replicas + 1,
                up_pressure=up_pressure, down_pressure=0.25,
                cooldown=5, registry=creg) if autoscale_on else None,
            actuator=act, spawn_engine=_spawn_engine, registry=creg)
        router.affinity = aff
        # the burst tenants carry NO rate limits — acceptance under
        # overload is decided by the brownout alone, so the per-tier
        # degradation law is not confounded by tier-blind throttling
        tenants["hi"] = TenantPolicy(priority=0)
        tenants["mid"] = TenantPolicy(priority=1)
        tenants["lo"] = TenantPolicy(priority=2)
    front = FrontDoor(router, auditor=ledger,
                      time_fn=lambda: clock["t"],
                      registry=MetricRegistry(),
                      flight_recorder=FlightRecorder(capacity=8),
                      tenants=tenants, control=control)

    n_req = int(rng.randint(4, 9))
    plan = []      # (arrival_t, pool_idx, max_new, deadline, tenant)
    t = 0.0
    for _ in range(n_req):
        t += float(rng.exponential(1.5))
        max_new = 1 if rng.random() < 0.2 \
            else int(rng.randint(2, _REF_HORIZON + 1))
        plan.append((t, int(rng.randint(0, len(pool))), max_new,
                     float(rng.randint(2, 18))
                     if rng.random() < 0.35 else None,
                     "b" if (tenants and rng.random() < 0.4) else "a"))
    # control-on episodes splice in the overload burst drawn from rng7
    # above: near-simultaneous arrivals cycling through the priority
    # tiers, biased toward the pool[5]/pool[6] shared-radix family so
    # prefix affinity has something warm to route to; re-sorted by
    # arrival. With control off the plan is byte-for-byte historical.
    if control is not None:
        tb = burst_t0
        for k in range(n_burst):
            tb += float(burst_dt[k])
            pi = (5, 6)[k % 2] if burst_affin[k] \
                else int(burst_idx[k])
            plan.append((tb, pi, int(burst_new[k]), None,
                         ("lo", "hi", "mid", "lo", "hi")[k % 5]))
        plan.sort(key=lambda e: e[0])
    cancels = []              # (submit order, loop iteration)
    if rng.random() < 0.3:
        cancels.append((int(rng.randint(0, n_req)),
                        int(rng.randint(1, 12))))
    disconnects = []          # explicit socket-gone (submit order, it)
    if rng.random() < 0.4:
        disconnects.append((int(rng.randint(0, n_req)),
                            int(rng.randint(1, 12))))
    # replica kills: flag kills between iterations, and mid-step kills
    # (ReplicaDead raised from INSIDE a replica's prefill/decode — the
    # mid-prefill / mid-stream death regime)
    kills = []                # (iteration, replica index)
    if rng.random() < 0.7:
        kills.append((int(rng.randint(2, 12)),
                      int(rng.randint(0, n_replicas))))
    if n_replicas > 2 and rng.random() < 0.25:
        kills.append((int(rng.randint(6, 16)),
                      int(rng.randint(0, n_replicas))))
    mid_kill = None
    if rng.random() < 0.5:
        point = ("serving.step.decode", "serving.step.prefill",
                 "serving.prefill.paged")[int(rng.randint(0, 3))]
        mid_kill = FaultArm(point, times=1,
                            after=int(rng.randint(0, 10)))
    schedule = _sample_arms(rng, [
        ("serving.step.decode", 0.4, (1, 3), (0, 8)),
        ("serving.step.prefill", 0.35, (1, 3), (0, 8)),
        ("serving.prefill.paged", 0.3, (1, 3), (0, 8)),
        ("router.dispatch", 0.35, (1, 2), (0, 6)),
        ("router.health_probe", 0.4, (1, 3), (0, 12)),
        ("frontdoor.stream_write", 0.4, (1, 3), (0, 10)),
        ("frontdoor.client_disconnect", 0.4, (1, 2), (0, 20)),
    ])
    # control-plane arms, from the rng7 stream that owns the control
    # draws (all draws above are unconditional; armed only when the
    # matching controller is on): shed fires inside the Actuator as
    # the brownout tries to refuse — containment means admission
    # FAILS OPEN (the request goes through); affinity/scale fire as
    # those actuations commit — containment keeps the least-loaded
    # pick / the current replica set (fail-static)
    if control is not None:
        if r_arm_shed < 0.5:
            schedule.append(FaultArm("control.shed", times=t_arm_shed,
                                     after=a_arm_shed))
        if affinity_on and r_arm_aff < 0.5:
            schedule.append(FaultArm("control.affinity",
                                     times=t_arm_aff,
                                     after=a_arm_aff))
        if autoscale_on and r_arm_scale < 0.5:
            schedule.append(FaultArm("control.scale", times=1,
                                     after=a_arm_scale))
    for arm in schedule:
        arm.arm()
    if mid_kill is not None:
        faults.inject(mid_kill.point, times=mid_kill.times,
                      after=mid_kill.after, exc=ReplicaDead)
        schedule = schedule + [mid_kill]
    shutdown_iter = int(rng.randint(2, 12)) \
        if rng.random() < 0.4 else None

    violations: List[str] = []
    submitted = []            # (handle, pool idx)
    rejected = 0
    sheds = 0
    tier_attempts: dict = {}  # tier -> admission attempts
    tier_accepted: dict = {}  # tier -> accepted (delivery follows)

    def _submit(pi, mn, dl, tenant):
        nonlocal rejected, sheds
        tr = int(tenants[tenant].priority) if tenant in tenants else 0
        tier_attempts[tr] = tier_attempts.get(tr, 0) + 1
        try:
            submitted.append(
                (front.submit(pool[pi], mn, tenant=tenant,
                              deadline_s=dl, stream=ClientStream()),
                 pi))
            tier_accepted[tr] = tier_accepted.get(tr, 0) + 1
        except Shed:
            rejected += 1     # audited via on_rejected, like the rest
            sheds += 1
        except (ServingError, ValueError, faults.InjectedFault):
            rejected += 1     # typed refusal: audited via on_rejected

    i = 0
    iters = 0
    try:
        while i < len(plan) or front.has_work():
            iters += 1
            if iters > max_iters:
                violations.append(
                    f"episode did not quiesce within {max_iters} "
                    f"iterations")
                break
            if shutdown_iter is not None and iters >= shutdown_iter:
                while i < len(plan):
                    _, pi, mn, dl, tn = plan[i]
                    _submit(pi, mn, dl, tn)
                    i += 1
                break
            clock["t"] += 1.0
            for at_iter, ridx in kills:
                if at_iter == iters:
                    router.replicas[ridx].kill()
            while i < len(plan) and plan[i][0] <= clock["t"]:
                _, pi, mn, dl, tn = plan[i]
                _submit(pi, mn, dl, tn)
                i += 1
            for order, at_iter in cancels:
                if at_iter == iters and order < len(submitted):
                    front.cancel(submitted[order][0])
            for order, at_iter in disconnects:
                if at_iter == iters and order < len(submitted):
                    front.disconnect(submitted[order][0])
            if front.has_work():
                front.pump()
        front.drain()
    except Exception as e:  # noqa: BLE001 — any escape breaks the
        violations.append(  # "the front door never strands work" law
            f"episode escaped with {type(e).__name__}: {e}")

    fired = faults.fired()
    faults.clear()
    violations += ledger.violations()
    violations += router_leak_violations(router)
    violations += frontdoor_leak_violations(front)
    violations += token_prefix_violations(
        (h.req, refs[pi]) for h, pi in submitted)
    # stream-consistency law: what a still-connected client SAW must
    # match the request's terminal state — streamed tokens are a
    # prefix of out_tokens, and the final event carries the full
    # output and finish reason
    for h, _ in submitted:
        evs = h.stream.events()
        toks = [e["token"] for e in evs if e["event"] == "token"]
        dones = [e for e in evs if e["event"] == "done"]
        if toks != list(h.req.out_tokens[:len(toks)]):
            violations.append(
                f"request {h.req.rid}: streamed tokens {toks} are "
                f"not a prefix of delivered {h.req.out_tokens}")
        if h.disconnected:
            continue
        if len(dones) != 1:
            violations.append(
                f"request {h.req.rid}: connected client got "
                f"{len(dones)} 'done' events (want exactly 1)")
        elif dones[0]["output_ids"] != h.req.output_ids \
                or dones[0]["finish_reason"] != h.req.finish_reason:
            violations.append(
                f"request {h.req.rid}: done event "
                f"{dones[0]['output_ids']}/{dones[0]['finish_reason']}"
                f" != request {h.req.output_ids}/"
                f"{h.req.finish_reason}")
    # graceful-degradation law: whenever the brownout shed ANYTHING,
    # tier 0 must never have been shed, and the shed RATE must be
    # monotone non-decreasing in tier number (tier 0 is the most
    # important) — brownout protects the top of the priority ladder,
    # whatever the fault weather did to the rest of the episode
    if control is not None and control.brownout is not None \
            and control.brownout.sheds > 0:
        by_tier = control.brownout.sheds_by_tier
        if by_tier.get(0, 0):
            violations.append(
                f"graceful degradation broken: tier 0 was shed "
                f"{by_tier[0]} times (must be never)")
        rates = {tr: by_tier.get(tr, 0) / tier_attempts[tr]
                 for tr in (0, 1, 2) if tier_attempts.get(tr)}
        for hi_t in (0, 1):
            for lo_t in range(hi_t + 1, 3):
                if hi_t in rates and lo_t in rates \
                        and rates[hi_t] > rates[lo_t] + 1e-9:
                    violations.append(
                        f"graceful degradation broken: tier {hi_t} "
                        f"shed rate {rates[hi_t]:.3f} > tier {lo_t} "
                        f"rate {rates[lo_t]:.3f}")
    deaths = sum(1 for r in router.replicas if r.state == "dead")
    brown = control.brownout if control is not None else None
    asc = control.autoscaler if control is not None else None
    return EpisodeResult(
        seed=seed, kind="frontdoor", violations=violations,
        schedule=schedule, fired=fired,
        stats={"requests": len(submitted), "rejected": rejected,
               "replicas": n_replicas, "replica_deaths": deaths,
               "failovers": int(router._m_failover.value),
               "failover_requests":
                   int(router._m_failover_req.value),
               "kills_scheduled": len(kills),
               "mid_kill": mid_kill.point if mid_kill else None,
               "attempts": ledger.attempts,
               "control_on": control is not None,
               "sheds": brown.sheds if brown is not None else 0,
               "sheds_by_tier": dict(brown.sheds_by_tier)
               if brown is not None else {},
               "brownout_level": brown.level
               if brown is not None else 0,
               "affinity_hits": (control.affinity.hits
                                 if control is not None
                                 and control.affinity is not None
                                 else 0),
               "scale_actions": asc.actions if asc is not None else 0,
               "scale_by_dir": dict(asc.actions_by_dir)
               if asc is not None else {},
               "replicas_final": sum(
                   1 for r in router.replicas if r.dispatchable),
               "tier_attempts": dict(tier_attempts),
               "tier_accepted": dict(tier_accepted),
               "actuator_faulted": (control.actuator.faulted
                                    if control is not None else 0)})


# ---------------------------------------------------------------------------
# cluster episodes (cross-process replicas, real kills)
# ---------------------------------------------------------------------------

_cluster_sup = None


def _shutdown_cluster() -> None:
    global _cluster_sup
    if _cluster_sup is not None:
        try:
            _cluster_sup.shutdown()
        except Exception:
            pass
        wdir = getattr(_cluster_sup, "_weight_store_dir", None)
        if wdir:
            import shutil
            shutil.rmtree(wdir, ignore_errors=True)
        _cluster_sup = None


def _cluster_supervisor():
    """The band-shared 2-worker cluster: spawning a worker process
    costs seconds (jax import + model build), so episodes re-arm the
    WARM pool via ``new_episode`` instead of paying it per seed."""
    global _cluster_sup
    if _cluster_sup is None:
        import atexit
        import tempfile
        from ..observability import (ClusterTelemetry, FlightRecorder,
                                     MetricRegistry)
        from ..serving.cluster import ClusterSupervisor
        spec = {"tiny": True, "model_seed": 0,
                "model_config": dict(
                    num_hidden_layers=1, hidden_size=32,
                    intermediate_size=64, num_attention_heads=2,
                    max_position_embeddings=_MAX_LEN),
                "engine": {"max_slots": 2, "max_len": _MAX_LEN,
                           "min_bucket": _MIN_BUCKET},
                "virtual_clock": True}
        # band-lived shared weight store: workers load by digest-
        # verified fetch (same bits as the seed rebuild, so the
        # cross-process token-identity law is unchanged) and every
        # engine reset re-verifies — the surface the
        # cluster.weights.fetch arms land on. Removed in
        # _shutdown_cluster: chaos must not litter the filesystem.
        _cluster_sup = ClusterSupervisor(
            spec, n_workers=2, max_respawns=8,
            registry=MetricRegistry(),
            flight_recorder=FlightRecorder(capacity=16),
            dump_on_death=False,
            telemetry=ClusterTelemetry(), scrape_interval=1,
            weight_store_dir=tempfile.mkdtemp(
                prefix="ptpu_chaos_weights_"))
        _cluster_sup.start()
        atexit.register(_shutdown_cluster)
    return _cluster_sup


def run_cluster_episode(seed: int, max_iters: int = 300,
                        respawn: bool = True) -> EpisodeResult:
    """One seeded CROSS-PROCESS episode: the front door + ledger from
    the frontdoor episodes, but the replicas are ``RemoteEngine``
    clients over real worker *processes* and the kills are real:

    - **coop** — ``Replica.kill()``: the router-side flag kill; the
      worker process stays warm and the supervisor soft-reclaims it
      with a ``reset`` RPC (fencing without a spawn).
    - **sigkill** — ``os.kill(pid, SIGKILL)``, either immediately or
      armed INSIDE the worker at a serving fault point (``kill=True``
      → the process dies mid-prefill / mid-decode). The supervisor
      pays a real process respawn.
    - **partition** — ``cluster.rpc.send``/``recv`` armed CLIENT-side
      past the retry budget: the socket dies mid-frame, retries
      exhaust, the replica goes ``ReplicaDead`` while the worker
      process is still alive — the supervisor must fence it.

    Failover + respawn run under the load; audits are the frontdoor
    set END-TO-END (ledger conservation, token identity vs the
    in-process reference replay — the cross-process identity law —
    stream consistency, router/front-door leaks) plus an in-worker
    page/slot-leak audit over the survivors. ``respawn=False`` turns
    the supervisor into fence-only (the pinned-red-seed mode)."""
    import signal as _signal
    from ..observability import FlightRecorder, MetricRegistry
    from ..serving import ClientStream, FrontDoor, ServingError, TenantPolicy

    refs = _reference_outputs()
    pool = _prompt_pool()
    faults.clear()
    faults.reset_counts()
    rng = np.random.RandomState(seed)
    ledger = ConservationLedger()
    clock = {"t": 0.0}
    sup = _cluster_supervisor()
    sup.respawn = respawn

    max_slots = int(rng.randint(1, 3))
    num_pages = int(rng.randint(_MAX_LEN // 8 + 1,
                                max_slots * (_MAX_LEN // 8) + 2))
    eng_kw = dict(max_slots=max_slots, max_len=_MAX_LEN,
                  min_bucket=_MIN_BUCKET, page_size=8,
                  num_pages=num_pages)
    donate = bool(rng.randint(0, 2))
    router = sup.new_episode(eng_kw, donate=donate, virtual_clock=True,
                             time_fn=lambda: clock["t"])
    # the supervisor's registry is band-lived: snapshot the router
    # counters so the stats below are THIS episode's deltas
    fail0 = int(router._m_failover.value)
    fail_req0 = int(router._m_failover_req.value)
    # watchtower over the SUPERVISOR registry (where the router's
    # death/failover counters live — band-lived, so the priming flush
    # below snapshots pre-episode history the same way fail0 does) +
    # the cluster telemetry plane for trace excerpts. Wall-clock
    # detectors are off for the same virtual-clock reason as the
    # serving band (_serving_watchtower docstring).
    from ..observability.watchtower import Watchtower
    wt = Watchtower(registry=sup.registry, objectives=(),
                    telemetry=sup.telemetry,
                    time_fn=lambda: clock["t"],
                    eval_interval_s=2.0, dedup_window_s=1e9,
                    stall_after_s=None, heartbeat_max_age_s=None,
                    anomaly_streams=False)
    wt.flush()                   # prime counter baselines
    tenants = {}
    if rng.random() < 0.5:
        tenants["b"] = TenantPolicy(
            rate_qps=float(rng.randint(1, 4)) / 4.0, burst=2,
            max_inflight=int(rng.randint(1, 4)))
    front = FrontDoor(router, auditor=ledger,
                      time_fn=lambda: clock["t"],
                      registry=MetricRegistry(),
                      flight_recorder=FlightRecorder(capacity=8),
                      tenants=tenants, watchtower=wt)

    n_req = int(rng.randint(4, 9))
    plan = []      # (arrival_t, pool_idx, max_new, deadline, tenant)
    t = 0.0
    for _ in range(n_req):
        t += float(rng.exponential(1.5))
        max_new = 1 if rng.random() < 0.2 \
            else int(rng.randint(2, _REF_HORIZON + 1))
        plan.append((t, int(rng.randint(0, len(pool))), max_new,
                     float(rng.randint(4, 20))
                     if rng.random() < 0.3 else None,
                     "b" if (tenants and rng.random() < 0.4) else "a"))
    cancels = []
    if rng.random() < 0.3:
        cancels.append((int(rng.randint(0, n_req)),
                        int(rng.randint(1, 12))))
    disconnects = []
    if rng.random() < 0.4:
        disconnects.append((int(rng.randint(0, n_req)),
                            int(rng.randint(1, 12))))
    # the three kill kinds, sampled independently (an episode may mix
    # them — or stay quiet); every draw happens HERE so the schedule
    # is a pure function of the seed
    kills = []     # (iteration, kind, live-replica pick)
    if rng.random() < 0.45:
        kills.append((int(rng.randint(2, 12)), "coop",
                      int(rng.randint(0, 8))))
    sig_point = ("serving.step.decode", "serving.step.prefill",
                 "serving.prefill.paged")[int(rng.randint(0, 3))]
    sig_immediate = bool(rng.randint(0, 2))
    sig_after = int(rng.randint(0, 4))
    if rng.random() < 0.45:
        kills.append((int(rng.randint(2, 14)), "sigkill",
                      int(rng.randint(0, 8))))
    part_point = CLUSTER_SWEEP[int(rng.randint(0, 2))]
    part_times = int(rng.randint(4, 8))     # > the 3-attempt budget
    part_after = int(rng.randint(0, 8))
    if rng.random() < 0.40:
        kills.append((int(rng.randint(2, 14)), "partition",
                      int(rng.randint(0, 8))))
    # non-fatal wire blips: below the retry budget, the client must
    # absorb them without the replica ever going suspect
    blips = _sample_arms(rng, [
        ("cluster.rpc.send", 0.3, (1, 3), (2, 24)),
        ("cluster.rpc.recv", 0.3, (1, 3), (2, 24)),
    ])
    # in-worker engine faults (typed InjectedFault over the wire →
    # the router's transient/broken handling + recover() RPC)
    worker_arm = None
    if rng.random() < 0.35:
        worker_arm = (int(rng.randint(0, sup.n_workers)),
                      ("serving.step.decode",
                       "serving.step.prefill")[int(rng.randint(0, 2))],
                      int(rng.randint(1, 3)), int(rng.randint(0, 6)))
    shutdown_iter = int(rng.randint(2, 12)) \
        if rng.random() < 0.3 else None
    # serving-fabric arms from a FIFTH rng stream appended AFTER every
    # pre-existing draw (pre-fabric seeds stay bit-identical): an
    # authenticated-framing blip below the RPC retry budget (the
    # client re-handshakes invisibly), an auth partition past it (the
    # exhausted counted rejection = ReplicaDead while the worker still
    # runs — the supervisor must fence), and a worker-side weight-
    # store arm the next digest-verified fetch (engine reset) absorbs
    # inside ITS retry budget
    rng5 = np.random.RandomState(1100000 + seed)
    auth_blip = rng5.random() < 0.35
    auth_times = int(rng5.randint(1, 3))      # < the 3-attempt budget
    auth_after = int(rng5.randint(2, 24))
    auth_part = rng5.random() < 0.25
    auth_part_at = int(rng5.randint(2, 14))
    auth_part_pick = int(rng5.randint(0, 8))
    auth_part_times = int(rng5.randint(4, 8))  # > the budget
    auth_part_after = int(rng5.randint(0, 8))
    weights_draw = rng5.random() < 0.4
    weights_widx = int(rng5.randint(0, sup.n_workers))
    weights_times = int(rng5.randint(1, 3))   # < the fetch budget
    if auth_part:
        kills.append((auth_part_at, "authpart", auth_part_pick))

    for arm in blips:
        arm.arm()
    schedule = list(blips)
    if auth_blip:
        arm = FaultArm("cluster.rpc.auth", times=auth_times,
                       after=auth_after)
        arm.arm()
        schedule.append(arm)
    if weights_draw:
        try:
            sup.workers[weights_widx].client.arm_fault(
                "cluster.weights.fetch", times=weights_times, after=0)
            schedule.append(FaultArm("cluster.weights.fetch",
                                     times=weights_times, after=0))
        except Exception:
            weights_draw = False
    if worker_arm is not None:
        widx, point, times, after = worker_arm
        try:
            sup.workers[widx].client.arm_fault(point, times=times,
                                               after=after)
            schedule.append(FaultArm(point, times=times, after=after))
        except Exception:
            worker_arm = None

    violations: List[str] = []
    submitted = []
    rejected = 0
    kind_counts = {"coop": 0, "sigkill": 0, "partition": 0,
                   "authpart": 0}

    def _submit(pi, mn, dl, tenant):
        nonlocal rejected
        try:
            submitted.append(
                (front.submit(pool[pi], mn, tenant=tenant,
                              deadline_s=dl, stream=ClientStream()),
                 pi))
        except (ServingError, ValueError, faults.InjectedFault):
            rejected += 1

    def _fire_kill(kind, pick):
        live = [r for r in router.replicas if r.state == "healthy"]
        if not live:
            return
        rep = live[pick % len(live)]
        kind_counts[kind] += 1
        if kind == "coop":
            rep.kill()
        elif kind == "sigkill":
            if sig_immediate or rep.handle.pid is None:
                try:
                    os.kill(rep.handle.pid, _signal.SIGKILL)
                except (OSError, TypeError):
                    pass
            else:
                try:
                    rep.engine.arm_fault(sig_point, times=1,
                                         after=sig_after, kill=True)
                    schedule.append(FaultArm(sig_point, times=1,
                                             after=sig_after))
                except Exception:
                    pass
        elif kind == "partition":    # client-side, fatal
            arm = FaultArm(part_point, times=part_times,
                           after=part_after)
            arm.arm()
            schedule.append(arm)
        else:                        # authpart: exhausted auth = wire
            #                          loss past the budget, fenced
            #                          exactly like a partition
            arm = FaultArm("cluster.rpc.auth", times=auth_part_times,
                           after=auth_part_after)
            arm.arm()
            schedule.append(arm)

    i = 0
    iters = 0
    try:
        while i < len(plan) or front.has_work():
            iters += 1
            if iters > max_iters:
                violations.append(
                    f"episode did not quiesce within {max_iters} "
                    f"iterations")
                break
            if shutdown_iter is not None and iters >= shutdown_iter:
                while i < len(plan):
                    _, pi, mn, dl, tn = plan[i]
                    _submit(pi, mn, dl, tn)
                    i += 1
                break
            clock["t"] += 1.0
            for at_iter, kind, pick in kills:
                if at_iter == iters:
                    _fire_kill(kind, pick)
            while i < len(plan) and plan[i][0] <= clock["t"]:
                _, pi, mn, dl, tn = plan[i]
                _submit(pi, mn, dl, tn)
                i += 1
            for order, at_iter in cancels:
                if at_iter == iters and order < len(submitted):
                    front.cancel(submitted[order][0])
            for order, at_iter in disconnects:
                if at_iter == iters and order < len(submitted):
                    front.disconnect(submitted[order][0])
            if front.has_work():
                front.pump()
            sup.poll()
            wt.poll()        # death counters advance in sup.poll()
        front.drain()
        sup.poll()
        sup.scrape_all()     # pick up spans from the drain's steps
    except Exception as e:  # noqa: BLE001 — any escape breaks the
        violations.append(  # "the cluster never strands work" law
            f"episode escaped with {type(e).__name__}: {e}")
    # two forced evaluations at quiesce: deaths the final sup.poll()
    # marked (and any orphan-style double-confirmation) land in this
    # episode's incident set before the stats snapshot
    wt.flush()
    wt.flush()

    fired = faults.fired()
    faults.clear()
    violations += ledger.violations()
    violations += timeline_violations(
        sup.telemetry,
        [ledger.submitted[rid] for rid in ledger.delivered
         if rid in ledger.submitted])
    violations += router_leak_violations(router)
    violations += frontdoor_leak_violations(front)
    violations += token_prefix_violations(
        (h.req, refs[pi]) for h, pi in submitted)
    for h, _ in submitted:
        evs = h.stream.events()
        toks = [e["token"] for e in evs if e["event"] == "token"]
        dones = [e for e in evs if e["event"] == "done"]
        if toks != list(h.req.out_tokens[:len(toks)]):
            violations.append(
                f"request {h.req.rid}: streamed tokens {toks} are "
                f"not a prefix of delivered {h.req.out_tokens}")
        if h.disconnected:
            continue
        if len(dones) != 1:
            violations.append(
                f"request {h.req.rid}: connected client got "
                f"{len(dones)} 'done' events (want exactly 1)")
        elif dones[0]["output_ids"] != h.req.output_ids \
                or dones[0]["finish_reason"] != h.req.finish_reason:
            violations.append(
                f"request {h.req.rid}: done event "
                f"{dones[0]['output_ids']}/{dones[0]['finish_reason']}"
                f" != request {h.req.output_ids}/"
                f"{h.req.finish_reason}")
    # in-worker audit: the mirror can't see device pools, so page
    # leaks after mid-prefill deaths are only visible from inside
    for slot in sup.workers:
        rep = slot.replica
        if rep is None or rep.state != "healthy" \
                or slot.client is None:
            continue
        try:
            violations += [f"worker {slot.wid}: {v}"
                           for v in slot.client.remote_audit()]
        except Exception as e:
            violations.append(
                f"worker {slot.wid}: remote audit failed with "
                f"{type(e).__name__}: {e}")
    deaths = sum(1 for r in router.replicas if r.state == "dead")
    return EpisodeResult(
        seed=seed, kind="cluster", violations=violations,
        schedule=schedule, fired=fired,
        stats={"requests": len(submitted), "rejected": rejected,
               "replica_deaths": deaths,
               "failovers": int(router._m_failover.value) - fail0,
               "failover_requests":
                   int(router._m_failover_req.value) - fail_req0,
               "kills": dict(kind_counts),
               "respawns": sup.respawns_used,
               "worker_arm": worker_arm,
               "auth_blip": auth_blip,
               "weights_arm": weights_draw,
               "attempts": ledger.attempts,
               "incidents": len(wt.incidents()),
               "incident_kinds": sorted(
                   {(inc.kind, inc.phase)
                    for inc in wt.incidents()})})


# ---------------------------------------------------------------------------
# training episodes
# ---------------------------------------------------------------------------

class ChaosStore:
    """Dict-backed TCPStore stand-in wired to the SAME ``store.*``
    fault points as the native client (distributed/store.py), so the
    chaos sweep exercises store-outage handling without a server."""

    def __init__(self):
        self._d = {}
        self.world_size = 1

    def set(self, k, v):
        faults.maybe_fail("store.set", key=k)
        self._d[k] = v if isinstance(v, bytes) else str(v).encode()

    def get(self, k, timeout=None):
        faults.maybe_fail("store.get", key=k)
        if k not in self._d:
            raise TimeoutError(f"no value for {k}")
        return self._d[k]

    def add(self, k, delta=1):
        faults.maybe_fail("store.add", key=k)
        cur = int(self._d.get(k, b"0")) + delta
        self._d[k] = str(cur).encode()
        return cur

    def wait(self, k, timeout=None):
        faults.maybe_fail("store.wait", key=k)
        if k not in self._d:
            raise TimeoutError(k)


class _PeeredWatchdog:
    """A world_size=2 CommWatchdog whose rank-1 peer is simulated:
    every beat also refreshes the peer's heartbeat through the (chaos)
    store, and check() reads peer ages first, so ``watchdog.beat`` AND
    ``store.set``/``store.get`` fault points all fire on the training
    loop's per-step watchdog path."""

    def __init__(self, store, registry, recorder):
        from ..distributed.watchdog import CommWatchdog
        self.store = store
        self.wd = CommWatchdog(store, rank=0, world_size=2,
                               timeout=3600.0, registry=registry,
                               flight_recorder=recorder)

    def beat(self):
        self.store.set("__watchdog__/hb/1",
                       repr(time.time()).encode())
        self.wd.beat()

    def check(self):
        # grace: an injected store outage must degrade to "peer in
        # startup grace", not kill the run — RetryingStore has already
        # absorbed what the retry budget covers
        self.wd.peer_ages(on_unreachable="grace")
        self.wd.check()


def _read_latest(ckpt_dir: str) -> Optional[int]:
    p = os.path.join(ckpt_dir, "LATEST")
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return int(f.read().strip())


def run_training_episode(seed: int, workdir: str,
                         num_steps: int = 12, save_every: int = 4,
                         max_relaunches: int = 8) -> EpisodeResult:
    """One seeded training episode: a ResilientTrainLoop over a
    deterministic numpy step function, with crashes injected into the
    step (``train.step``), the simulated data pipeline
    (``io.dataloader.worker``), checkpoint shard writes and the commit
    point, watchdog beats, and every chaos-store op. An exception that
    escapes ``run()`` is treated as a process crash: the loop is
    relaunched with FRESH state (memory is gone) and auto-resumes from
    the LATEST published checkpoint — in-process recovery and relaunch
    recovery share one on-disk format, and both must preserve the loss
    trajectory."""
    from .retry import RetryPolicy, RetryingStore
    from .train_loop import ResilientTrainLoop
    from ..distributed.checkpoint import wait_for_pending_saves
    from ..observability import FlightRecorder, MetricRegistry

    faults.clear()
    faults.reset_counts()
    rng = np.random.RandomState(seed)
    threads_before = list(threading.enumerate())
    ckpt_dir = os.path.join(workdir, f"chaos_train_{seed}")
    data = np.random.RandomState(20240 + 7).randn(32, 4) \
        .astype(np.float32)

    def fresh_state():
        return {"w": np.zeros((4,), np.float32), "seen": 0}

    def step_fn(state, step):
        # the dataloader-worker fault point fires where a dead worker
        # process surfaces in a real run: inside the step, before the
        # update — recoverable, replayed from the last checkpoint
        faults.maybe_fail("io.dataloader.worker", step=step)
        g = data[step % len(data)]
        state["w"] = state["w"] - 0.1 * (state["w"] - g)
        state["seen"] = int(state["seen"]) + 1
        return float(np.sum(state["w"] ** 2))

    # uninjected baseline (no rules armed yet: maybe_fail is a no-op)
    base_state = fresh_state()
    base_losses = [(s, step_fn(base_state, s))
                   for s in range(num_steps)]

    # crash-type faults must land AFTER the first publishable
    # checkpoint exists (a crash before it is typed-fatal by design);
    # retryable-I/O faults stay under the retry budgets so schedules
    # are survivable by construction — what is being tested is that
    # the SURVIVAL bookkeeping never loses or corrupts anything
    schedule = _sample_arms(rng, [
        ("train.step", 0.5, (1, 3), (save_every, num_steps)),
        ("io.dataloader.worker", 0.35, (1, 2),
         (save_every + 1, num_steps + 4)),
        ("checkpoint.shard_write", 0.5, (1, 5), (0, 6)),
        ("checkpoint.commit", 0.4, (1, 2), (0, 3)),
        ("watchdog.beat", 0.5, (1, 3), (0, num_steps)),
        ("store.set", 0.35, (1, 3), (0, 12)),
        ("store.get", 0.35, (1, 3), (0, 12)),
        ("store.add", 0.3, (1, 2), (0, 4)),
        ("store.wait", 0.3, (1, 2), (0, 4)),
    ])
    for arm in schedule:
        arm.arm()

    reg = MetricRegistry()
    no_sleep = lambda d: None          # noqa: E731 — injected sleep
    store = RetryingStore(ChaosStore(), RetryPolicy(
        max_attempts=4, base_delay=0.001, jitter=0.0,
        sleep_fn=no_sleep,
        retry_on=(ConnectionError, OSError, faults.InjectedFault),
        no_retry_on=(TimeoutError,), registry=reg))
    recorder = FlightRecorder(capacity=8)
    watchdog = _PeeredWatchdog(store, reg, recorder)
    retry_pol = RetryPolicy(
        max_attempts=4, base_delay=0.001, jitter=0.0,
        sleep_fn=no_sleep, registry=reg)

    violations: List[str] = []
    reports: List[dict] = []
    latest_history: List[Optional[int]] = []
    crashes: List[str] = []
    state = None
    completed = False
    for _ in range(max_relaunches):
        state = fresh_state()          # relaunch: memory is gone
        loop = ResilientTrainLoop(
            step_fn, state, ckpt_dir, save_every=save_every,
            watchdog=watchdog, max_recoveries=10,
            retry_policy=retry_pol, registry=MetricRegistry(),
            flight_recorder=FlightRecorder(capacity=32))
        try:
            reports.append(loop.run(num_steps))
            latest_history.append(_read_latest(ckpt_dir))
            completed = True
            break
        except Exception as e:  # noqa: BLE001 — "process crash"
            crashes.append(f"{type(e).__name__}: {e}")
            latest_history.append(_read_latest(ckpt_dir))
        # store health probe between relaunches: exercises add/wait
        # through the retry wrapper (absorbed by budget construction)
        try:
            store.add("__chaos__/relaunches", 1)
            store.wait("__chaos__/relaunches")
        except Exception as e:  # noqa: BLE001
            violations.append(f"store probe escaped retries: "
                              f"{type(e).__name__}: {e}")
    if not completed:
        violations.append(
            f"training did not converge within {max_relaunches} "
            f"relaunches (crashes: {crashes})")

    # settle every async save; each call may deliver one previously
    # unobserved writer error (that IS the surfacing contract)
    for _ in range(8):
        try:
            wait_for_pending_saves(timeout=60.0)
            break
        except TimeoutError:
            violations.append("async saves still writing after the "
                              "episode settled")
            break
        except Exception:
            continue
    fired = faults.fired()
    faults.clear()

    violations += pending_save_violations()
    violations += thread_leak_violations(threads_before)
    violations += loss_trajectory_violations(reports, base_losses)
    if completed:
        if not np.array_equal(state["w"], base_state["w"]):
            violations.append(
                "final weights diverged from the uninjected baseline")
        violations += checkpoint_monotonic_violations(
            ckpt_dir,
            lambda: {"state": fresh_state(), "step": 0},
            latest_history, expect_final=num_steps)
    return EpisodeResult(
        seed=seed, kind="training", violations=violations,
        schedule=schedule, fired=fired,
        stats={"relaunches": len(crashes), "crashes": crashes,
               "recoveries": sum(r["recoveries"] for r in reports),
               "num_steps": num_steps})


def run_episode(seed: int, kind: str, workdir: Optional[str] = None) \
        -> EpisodeResult:
    """Dispatch one episode; training episodes need a ``workdir``."""
    if kind == "serving":
        return run_serving_episode(seed)
    if kind == "frontdoor":
        return run_frontdoor_episode(seed)
    if kind == "cluster":
        return run_cluster_episode(seed)
    if kind == "training":
        if workdir is None:
            raise ValueError("training episodes need a workdir")
        return run_training_episode(seed, workdir)
    raise ValueError(f"unknown episode kind {kind!r}")
