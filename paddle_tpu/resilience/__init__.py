"""paddle_tpu.resilience — make failures survivable, and prove it.

Four pieces (docs/RESILIENCE.md has the full guide):

- **Fault injection** (``faults``): named fault points wired into the
  serving step, prefill, TCPStore client ops, checkpoint shard writes,
  the commit point, watchdog heartbeats, and dataloader workers;
  armed programmatically or via ``PTPU_FAULTS``. Every recovery path
  below is exercised on CPU by injecting the failure it survives.
- **RetryPolicy** (``retry``): exponential backoff + seeded jitter,
  deadline-aware, per-attempt metrics; ``RetryingStore`` applies it to
  TCPStore get/set/add/wait, and checkpoint shard I/O retries through
  the same class.
- **Serving recovery** (``serving.engine``): ``recover()`` rebuilds
  the slot-pool KV cache from host-side request state and re-prefills
  in-flight requests (greedy replay verified token-identical), plus
  request deadlines, a bounded admission queue (typed ``QueueFull``),
  and ``drain()`` — see ``paddle_tpu.serving.errors``.
- **ResilientTrainLoop** (``train_loop``): watchdog check + periodic
  async checkpoints + restore-latest-then-continue, on the
  ElasticManager checkpoint layout.
- **Chaos soak** (``chaos`` + ``invariants``): a seeded scheduler
  samples randomized fault schedules over every registered point
  (``faults.KNOWN_POINTS``) and drives full serving/training
  episodes, then asserts the end-to-end conservation invariants —
  exactly-once request delivery, greedy token identity, loss
  continuity, checkpoint monotonicity, no leaks. A red episode is a
  seed: one line reproduces it.

This package is stdlib-only at import time (``train_loop``,
``chaos`` and ``invariants`` load lazily), so dataloader worker
processes and the TCPStore client can import fault points without
dragging in jax or numpy.
"""
from . import faults  # noqa: F401
from .faults import InjectedFault, maybe_fail  # noqa: F401
from .retry import RetryError, RetryPolicy, RetryingStore  # noqa: F401

__all__ = ["faults", "InjectedFault", "maybe_fail", "RetryError",
           "RetryPolicy", "RetryingStore", "ResilientTrainLoop",
           "TrainLoopError", "RestartLimitExceeded", "train_loop",
           "chaos", "invariants", "ConservationLedger",
           "InvariantViolation"]

_LAZY = {"ResilientTrainLoop": "train_loop",
         "TrainLoopError": "train_loop",
         "RestartLimitExceeded": "train_loop",
         "train_loop": "train_loop",
         "chaos": "chaos",
         "invariants": "invariants",
         "ConservationLedger": "invariants",
         "InvariantViolation": "invariants"}


def __getattr__(name):
    # train_loop pulls in distributed.checkpoint (jax), chaos pulls in
    # numpy/serving — load lazily so importing the fault/retry
    # primitives stays dependency-free.
    # importlib, NOT `from . import`: the fromlist machinery getattrs
    # the package, which would re-enter this hook and recurse
    modname = _LAZY.get(name)
    if modname is not None:
        import importlib
        mod = importlib.import_module("." + modname, __name__)
        return mod if name == modname else getattr(mod, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
