"""paddle_tpu.resilience — make failures survivable, and prove it.

Four pieces (docs/RESILIENCE.md has the full guide):

- **Fault injection** (``faults``): named fault points wired into the
  serving step, prefill, TCPStore client ops, checkpoint shard writes,
  the commit point, watchdog heartbeats, and dataloader workers;
  armed programmatically or via ``PTPU_FAULTS``. Every recovery path
  below is exercised on CPU by injecting the failure it survives.
- **RetryPolicy** (``retry``): exponential backoff + seeded jitter,
  deadline-aware, per-attempt metrics; ``RetryingStore`` applies it to
  TCPStore get/set/add/wait, and checkpoint shard I/O retries through
  the same class.
- **Serving recovery** (``serving.engine``): ``recover()`` rebuilds
  the slot-pool KV cache from host-side request state and re-prefills
  in-flight requests (greedy replay verified token-identical), plus
  request deadlines, a bounded admission queue (typed ``QueueFull``),
  and ``drain()`` — see ``paddle_tpu.serving.errors``.
- **ResilientTrainLoop** (``train_loop``): watchdog check + periodic
  async checkpoints + restore-latest-then-continue, on the
  ElasticManager checkpoint layout.

This package is stdlib-only at import time (``train_loop`` loads
lazily), so dataloader worker processes and the TCPStore client can
import fault points without dragging in jax.
"""
from . import faults  # noqa: F401
from .faults import InjectedFault, maybe_fail  # noqa: F401
from .retry import RetryError, RetryPolicy, RetryingStore  # noqa: F401

__all__ = ["faults", "InjectedFault", "maybe_fail", "RetryError",
           "RetryPolicy", "RetryingStore", "ResilientTrainLoop",
           "TrainLoopError", "RestartLimitExceeded", "train_loop"]

_LAZY = {"ResilientTrainLoop", "TrainLoopError", "RestartLimitExceeded"}


def __getattr__(name):
    # train_loop pulls in distributed.checkpoint (jax) — load lazily so
    # importing the fault/retry primitives stays dependency-free.
    # importlib, NOT `from . import`: the fromlist machinery getattrs
    # the package, which would re-enter this hook and recurse
    if name in _LAZY or name == "train_loop":
        import importlib
        mod = importlib.import_module(".train_loop", __name__)
        return mod if name == "train_loop" else getattr(mod, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
